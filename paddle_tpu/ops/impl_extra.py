"""Op implementations, batch 2: the round-2 surface expansion.

Same conventions as impl.py (pure jittable functions over jax arrays; NCHW;
names match ops.yaml). Reference kernels: paddle/phi/kernels/* per-op files
named after each op (e.g. cpu/svd_kernel.cc, gpu/grid_sample_kernel.cu,
impl/fold_kernel_impl.h); semantics follow the phi InferMeta + kernel pair,
not torch (e.g. lu pivots are 1-based, huber_loss returns the residual).
"""

from __future__ import annotations

import builtins
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.ops.impl import _pair

# ============================================================== linalg family


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def eig(x):
    return jnp.linalg.eig(x)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def lu(x, pivot=True):
    """Returns (lu, pivots, info); pivots 1-based int32 per the reference
    phi LuKernel (LAPACK convention)."""
    import jax.scipy.linalg as jsl

    lu_mat, piv = jsl.lu_factor(x)
    return lu_mat, (piv + 1).astype(jnp.int32), jnp.zeros(
        x.shape[:-2], jnp.int32)


def lu_unpack(lu_mat, pivots, unpack_ludata=True, unpack_pivots=True):
    n = lu_mat.shape[-2]
    k = min(lu_mat.shape[-2], lu_mat.shape[-1])
    l = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(
        n, k, dtype=lu_mat.dtype)
    u = jnp.triu(lu_mat[..., :k, :])
    # pivots (1-based) -> permutation matrix
    piv = pivots.astype(jnp.int32) - 1

    def perm_of(piv1):
        p = jnp.arange(n)

        def body(i, p):
            j = piv1[i]
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)

        return lax.fori_loop(0, piv1.shape[0], body, p)

    if piv.ndim == 1:
        perm = perm_of(piv)
        pmat = jnp.eye(n, dtype=lu_mat.dtype)[perm]
    else:
        perm = jax.vmap(perm_of)(piv.reshape(-1, piv.shape[-1]))
        pmat = jnp.eye(n, dtype=lu_mat.dtype)[perm].reshape(
            piv.shape[:-1] + (n, n))
    return pmat.swapaxes(-1, -2), l, u


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


def solve(x, y):
    return jnp.linalg.solve(x, y)


def cholesky_solve(x, y, upper=False):
    """Solve A z = x given y = Cholesky factor of A (phi CholeskySolve)."""
    import jax.scipy.linalg as jsl

    return jsl.cho_solve((y, not upper), x)


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, tol=tol)


def multi_dot(xs):
    return jnp.linalg.multi_dot(list(xs))


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def p_norm(x, porder=2.0, axis=-1, epsilon=1e-12, keepdim=False,
           asvector=False):
    if asvector:
        x = x.reshape(-1)
        axis = 0
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    if porder == float("inf"):
        out = jnp.max(jnp.abs(xf), axis=axis, keepdims=keepdim)
    elif porder == float("-inf"):
        out = jnp.min(jnp.abs(xf), axis=axis, keepdims=keepdim)
    elif porder == 0:
        out = jnp.sum((xf != 0).astype(xf.dtype), axis=axis,
                      keepdims=keepdim)
    else:
        out = jnp.sum(jnp.abs(xf) ** porder, axis=axis,
                      keepdims=keepdim) ** (1.0 / porder)
    return out.astype(x.dtype)


def frobenius_norm(x, axis=None, keepdim=False, reduce_all=False):
    if reduce_all or axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


def dist(x, y, p=2.0):
    d = (x - y).reshape(-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


def nextafter(x, y):
    return jnp.nextafter(x, y)


# ================================================================== creation


def empty(shape, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.zeros(tuple(shape), to_jax_dtype(dtype))


def empty_like(x, dtype=None):
    return jnp.zeros_like(x) if dtype is None else jnp.zeros(
        x.shape, dtype)


def eye(num_rows, num_columns=None, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.eye(int(num_rows),
                   int(num_columns) if num_columns is not None else None,
                   dtype=to_jax_dtype(dtype))


def full(shape, fill_value, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.full(tuple(shape), fill_value, to_jax_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value,
                         dtype=None if dtype is None else dtype)


def linspace(start, stop, num, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.linspace(start, stop, int(num),
                        dtype=to_jax_dtype(dtype))


def logspace(start, stop, num, base=10.0, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=to_jax_dtype(dtype))


def meshgrid(xs):
    return tuple(jnp.meshgrid(*xs, indexing="ij"))


def ones(shape, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.ones(tuple(shape), to_jax_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=dtype)


def zeros(shape, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return jnp.zeros(tuple(shape), to_jax_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=dtype)


def tril_indices(rows, cols, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(int(rows), int(offset), int(cols))
    return jnp.stack([r, c]).astype(dtype)


def triu_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(int(row), int(offset), int(col))
    return jnp.stack([r, c]).astype(dtype)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    out_shape = x.shape[:-1] + (n, n)
    out = jnp.zeros(out_shape, x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = out.at[..., r, c].set(x)
    # move the two new axes into requested positions
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


# ==================================================================== random


def bernoulli(x, key):
    return jax.random.bernoulli(key, x).astype(x.dtype)


def binomial(count, key, prob=None):
    # paddle.binomial(count, prob): both tensors
    return jax.random.binomial(key, count.astype(jnp.float32),
                               prob.astype(jnp.float32)).astype(jnp.int64)


def dirichlet(alpha, key):
    return jax.random.dirichlet(key, alpha.astype(jnp.float32)).astype(
        alpha.dtype)


def exponential_(x, key, lam=1.0):
    return (jax.random.exponential(key, x.shape, jnp.float32)
            / lam).astype(x.dtype)


def gumbel_softmax(x, key, temperature=1.0, hard=False, axis=-1):
    g = jax.random.gumbel(key, x.shape, jnp.float32)
    y = jax.nn.softmax((x.astype(jnp.float32) + g) / temperature, axis=axis)
    if hard:
        onehot = (y == jnp.max(y, axis=axis, keepdims=True)).astype(y.dtype)
        y = lax.stop_gradient(onehot - y) + y  # straight-through estimator
    return y.astype(x.dtype)


def multinomial(x, key, num_samples=1, replacement=False):
    logits = jnp.log(jnp.maximum(x.astype(jnp.float32), 1e-30))
    if replacement:
        # draw along a leading sample axis (broadcast-compatible with the
        # batch shape), then move it last — paddle returns [..., samples]
        out = jnp.moveaxis(
            jax.random.categorical(key, logits, axis=-1,
                                   shape=(num_samples,) + x.shape[:-1]),
            0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, x.shape, jnp.float32)
        _, out = lax.top_k(logits + g, num_samples)
    return out.astype(jnp.int64)


def poisson(x, key):
    return jax.random.poisson(key, x.astype(jnp.float32),
                              dtype=jnp.int32).astype(x.dtype)


def standard_gamma(x, key):
    return jax.random.gamma(key, x.astype(jnp.float32)).astype(x.dtype)


def rrelu(x, key, lower=1.0 / 8, upper=1.0 / 3, training=True):
    if training:
        a = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, (a * x.astype(jnp.float32)).astype(x.dtype))


def gaussian(shape, key, mean=0.0, std=1.0, dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    dt = to_jax_dtype(dtype)
    return mean + std * jax.random.normal(key, tuple(shape), dt)


def uniform(shape, key, dtype="float32", min=-1.0, max=1.0):  # noqa: A002
    from paddle_tpu.core.dtype import to_jax_dtype

    return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype),
                              min, max)


def randint(low, key, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return jax.random.randint(key, tuple(shape), low, high).astype(dtype)


def randperm(n, key, dtype="int64"):
    return jax.random.permutation(key, int(n)).astype(dtype)


def truncated_gaussian_random(shape, key, mean=0.0, std=1.0, a=-2.0, b=2.0,
                              dtype="float32"):
    from paddle_tpu.core.dtype import to_jax_dtype

    return (mean + std * jax.random.truncated_normal(
        key, a, b, tuple(shape), jnp.float32)).astype(to_jax_dtype(dtype))


# =================================================================== bitwise


def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


def bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_left_shift(x, y, is_arithmetic=True):
    return jnp.left_shift(x, y)


_UNSIGNED = {jnp.dtype(jnp.int8): jnp.uint8,
             jnp.dtype(jnp.int16): jnp.uint16,
             jnp.dtype(jnp.int32): jnp.uint32,
             jnp.dtype(jnp.int64): jnp.uint64}


def bitwise_right_shift(x, y, is_arithmetic=True):
    if is_arithmetic:
        return jnp.right_shift(x, y)
    u = _UNSIGNED.get(jnp.dtype(x.dtype))
    ux = x.view(u) if u is not None else x
    return jnp.right_shift(ux, y.view(u) if u is not None and
                           y.dtype == x.dtype else y).astype(x.dtype)


# ============================================================== unary extras


def copysign(x, y):
    return jnp.copysign(x, y)


def gammaln(x):
    return jax.scipy.special.gammaln(x)


def i0(x):
    return jax.scipy.special.i0(x)


def i0e(x):
    return jax.scipy.special.i0e(x)


def i1(x):
    return jax.scipy.special.i1(x)


def i1e(x):
    return jax.scipy.special.i1e(x)


def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


def polygamma(x, n=0):
    if n == 0:
        return jax.scipy.special.digamma(x)
    return jax.scipy.special.polygamma(n, x)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def tanh_shrink(x):
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, jnp.asarray(value, x.dtype))


def increment(x, value=1.0):
    return x + jnp.asarray(value, x.dtype)


# ==================================================================== losses


def bce_loss(input, label):  # noqa: A002
    x = jnp.clip(input.astype(jnp.float32), 1e-12, 1 - 1e-7)
    out = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    return out.astype(input.dtype)


def hinge_loss(logits, labels):
    return jnp.maximum(
        1.0 - logits.astype(jnp.float32) * labels.astype(jnp.float32),
        0.0).astype(logits.dtype)


def huber_loss(input, label, delta=1.0):  # noqa: A002
    """Returns (out, residual) per phi HuberLossKernel."""
    residual = (label - input).astype(jnp.float32)
    a = jnp.abs(residual)
    out = jnp.where(a <= delta, 0.5 * residual * residual,
                    delta * (a - 0.5 * delta))
    return out.astype(input.dtype), residual.astype(input.dtype)


def kldiv_loss(x, target, reduction="mean", log_target=False):
    t = target.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    if log_target:
        out = jnp.exp(t) * (t - xf)
    else:
        out = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-30)) - xf),
                        0.0)
    if reduction == "mean":
        return jnp.mean(out).astype(x.dtype)
    if reduction == "batchmean":
        return (jnp.sum(out) / x.shape[0]).astype(x.dtype)
    if reduction == "sum":
        return jnp.sum(out).astype(x.dtype)
    return out.astype(x.dtype)


def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    x = input.astype(jnp.float32)
    out = (-label * jnp.log(x + epsilon)
           - (1 - label) * jnp.log(1 - x + epsilon))
    return out.astype(input.dtype)


def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100):
    xf = x.astype(jnp.float32)
    lf = label.astype(jnp.float32)
    out = jnp.maximum(xf, 0) - xf * lf + jnp.log1p(jnp.exp(-jnp.abs(xf)))
    mask = (lf != ignore_index)
    out = jnp.where(mask, out, 0.0)
    if normalize:
        out = out / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
    return out.astype(x.dtype)


def identity_loss(x, reduction="none"):
    if reduction in ("mean", 0):
        return jnp.mean(x)
    if reduction in ("sum", 1):
        return jnp.sum(x)
    return x


def cross_entropy_with_softmax(logits, label, soft_label=False,
                               use_softmax=True, numeric_stable_mode=True,
                               ignore_index=-100, axis=-1):
    """Returns (softmax, loss) per phi CrossEntropyWithSoftmaxKernel."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
        if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
    sm = jnp.exp(lp)
    if soft_label:
        loss = -jnp.sum(label * lp, axis=axis, keepdims=True)
    else:
        lbl = label.astype(jnp.int32)
        squeeze = lbl.ndim == logits.ndim
        idx = lbl if squeeze else lbl[..., None]
        picked = jnp.take_along_axis(lp, jnp.maximum(idx, 0), axis=axis)
        loss = jnp.where(idx == ignore_index, 0.0, -picked)
    return sm.astype(logits.dtype), loss.astype(logits.dtype)


# ============================================================== manipulation


def as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def complex(real, imag):  # noqa: A001
    return lax.complex(real, imag)


def as_strided(x, shape, stride, offset=0):
    """Functional as_strided: gather from the flat buffer (phi stride
    kernels collapse to gathers on TPU — no aliasing views in XLA)."""
    flat = x.reshape(-1)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * st
    return flat[idx.reshape(tuple(shape))]


def broadcast_tensors(xs):
    shape = jnp.broadcast_shapes(*[x.shape for x in xs])
    return tuple(jnp.broadcast_to(x, shape) for x in xs)


def crop(x, shape=None, offsets=None):
    offsets = offsets or [0] * x.ndim
    shape = shape or x.shape
    idx = tuple(builtins.slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, shape))
    return x[idx]


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def fill(x, value):
    return jnp.full_like(x, value)


def fill_diagonal(x, value=0.0, offset=0, wrap=False):
    rows, cols = x.shape[-2], x.shape[-1]
    n = min(rows, cols)
    i = jnp.arange(n)
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    ok = (r < rows) & (c < cols)
    r, c = jnp.where(ok, r, 0), jnp.where(ok, c, 0)
    upd = jnp.where(ok, jnp.asarray(value, x.dtype), x[..., r, c])
    out = x.at[..., r, c].set(upd)
    if wrap and x.ndim == 2 and rows > cols:
        # wrap the diagonal around tall matrices (numpy fill_diagonal)
        for start in range(cols + 1, rows, cols + 1):
            m = min(cols, rows - start)
            out = out.at[start:start + m, :m].set(
                jnp.where(jnp.eye(m, dtype=bool),
                          jnp.asarray(value, x.dtype),
                          out[start:start + m, :m]))
    return out


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    nd = x.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    xm = jnp.moveaxis(x, (d1, d2), (nd - 2, nd - 1))
    rows, cols = xm.shape[-2], xm.shape[-1]
    n = min(rows - max(-offset, 0), cols - max(offset, 0))
    i = jnp.arange(n)
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    ym = jnp.moveaxis(y, -1, y.ndim - 1) if y.ndim else y
    xm = xm.at[..., r, c].set(ym)
    return jnp.moveaxis(xm, (nd - 2, nd - 1), (d1, d2))


def index_add(x, index, add_value, axis=0):
    return x.at[(builtins.slice(None),) * (axis % x.ndim)
                + (index,)].add(add_value)


def index_put(x, indices, value, accumulate=False):
    idx = tuple(indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


def reverse(x, axis):
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return jnp.flip(x, axis=axis)


def sequence_mask(x, max_len=None, out_dtype="int64"):
    m = int(max_len) if max_len is not None else None
    if m is None:
        raise ValueError("sequence_mask requires max_len under jit "
                         "(value-dependent output shape otherwise)")
    return (jnp.arange(m) < x[..., None]).astype(out_dtype)


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards  # ceil (phi ShardIndex)
    in_shard = (x // size) == shard_id
    return jnp.where(in_shard, x % size, ignore_value)


def slice(x, axes, starts, ends):  # noqa: A001
    slices = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        slices[ax] = builtins.slice(int(st), int(en))
    return x[tuple(slices)]


def strided_slice(x, axes, starts, ends, strides):
    slices = [builtins.slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        slices[ax] = builtins.slice(int(st), int(en), int(sr))
    return x[tuple(slices)]


def split_with_num(x, num, axis=0):
    return tuple(jnp.split(x, int(num), axis=int(axis)))


def multiplex(inputs, index):
    stacked = jnp.stack(list(inputs))           # [K, N, ...]
    idx = index.reshape(-1).astype(jnp.int32)   # [N]
    return stacked[idx, jnp.arange(stacked.shape[1])]


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64"):
    flat = x.reshape(-1) if axis is None else x
    keep = jnp.concatenate([jnp.array([True]), flat[1:] != flat[:-1]])
    out = flat[np.asarray(keep)]
    rets = (out,)
    if return_inverse:
        inv = jnp.cumsum(keep.astype(dtype)) - 1
        rets += (inv,)
    if return_counts:
        idx = np.flatnonzero(np.asarray(keep))
        counts = jnp.asarray(np.diff(np.append(idx, flat.shape[0])),
                             dtype=dtype)
        rets += (counts,)
    return rets if len(rets) > 1 else out


# ======================================================== reductions / checks


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def mean_all(x):
    return jnp.mean(x)


def numel(x):
    return jnp.asarray(x.size, jnp.int64)


def shape(x):
    return jnp.asarray(x.shape, jnp.int32)


def is_empty(x):
    return jnp.asarray(x.size == 0)


def nanmedian(x, axis=None, keepdim=False, mode="avg"):
    return jnp.nanmedian(x, axis=tuple(axis) if isinstance(
        axis, (list, tuple)) else axis, keepdims=keepdim)


def _cum_with_idx(x, axis, better):
    axis = axis % x.ndim if axis is not None else 0

    def combine(a, b):
        av, ai = a
        bv, bi = b
        take_b = better(bv, av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    idx = jnp.broadcast_to(
        jnp.arange(x.shape[axis]).reshape(
            (1,) * axis + (-1,) + (1,) * (x.ndim - axis - 1)), x.shape)
    vals, idxs = lax.associative_scan(combine, (x, idx), axis=axis)
    return vals, idxs.astype(jnp.int64)


def cummax(x, axis=None, dtype="int64"):
    flat = axis is None
    xx = x.reshape(-1) if flat else x
    v, i = _cum_with_idx(xx, 0 if flat else axis, lambda b, a: b > a)
    return v, i


def cummin(x, axis=None, dtype="int64"):
    flat = axis is None
    xx = x.reshape(-1) if flat else x
    v, i = _cum_with_idx(xx, 0 if flat else axis, lambda b, a: b < a)
    return v, i


def l1_norm(x):
    return jnp.sum(jnp.abs(x))


def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.maximum(jnp.sum(jnp.square(x)), 1e-12))
    scale = jnp.minimum(max_norm / norm, 1.0)
    return x * scale


# ========================================================== vision / signal


def affine_grid(theta, out_shape, align_corners=True):
    n, _, h, w = (int(s) for s in out_shape)

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        return (jnp.arange(size) * 2 + 1) / size - 1.0

    ys = axis_coords(h)
    xs = axis_coords(w)
    gx, gy = jnp.meshgrid(xs, ys)              # [h, w]
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]
    grid = jnp.einsum("hwk,nck->nhwc", base, theta.astype(jnp.float32))
    return grid.astype(theta.dtype)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """NCHW input, [N,H,W,2] grid in [-1,1] (phi GridSampleKernel)."""
    n, c, h, w = x.shape
    gx = grid[..., 0].astype(jnp.float32)
    gy = grid[..., 1].astype(jnp.float32)

    def unnorm(g, size):
        if align_corners:
            return (g + 1) / 2 * (size - 1)
        return ((g + 1) * size - 1) / 2

    fx = unnorm(gx, w)
    fy = unnorm(gy, h)
    if padding_mode == "border":
        fx = jnp.clip(fx, 0, w - 1)
        fy = jnp.clip(fy, 0, h - 1)
    elif padding_mode == "reflection":
        def reflect(g, size):
            if align_corners:
                span = 2 * (size - 1)
                g = jnp.abs(g) % jnp.maximum(span, 1)
                return jnp.where(g > size - 1, span - g, g)
            span = 2 * size
            g = (jnp.abs(g + 0.5) % span)
            g = jnp.where(g > size, span - g, g) - 0.5
            return jnp.clip(g, 0, size - 1)

        fx = reflect(fx, w)
        fy = reflect(fy, h)

    def sample_at(ix, iy):
        inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        vals = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [N,Ho,Wo,C]
        if padding_mode == "zeros":
            vals = jnp.where(inb[..., None], vals, 0.0)
        return vals

    if mode == "nearest":
        out = sample_at(jnp.round(fx).astype(jnp.int32),
                        jnp.round(fy).astype(jnp.int32))
    else:
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = fx - x0
        wy = fy - y0
        out = (sample_at(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
               + sample_at(x1, y0) * (wx * (1 - wy))[..., None]
               + sample_at(x0, y1) * ((1 - wx) * wy)[..., None]
               + sample_at(x1, y1) * (wx * wy)[..., None])
    return jnp.moveaxis(out, -1, 1).astype(x.dtype)  # NHWC -> NCHW


def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w).swapaxes(
            1, 2).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups).swapaxes(
        3, 4).reshape(n, h, w, c)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        return x.transpose(0, 1, 3, 5, 2, 4).reshape(
            n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(
        n, h // r, w // r, c * r * r)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im: inverse of unfold (phi FoldKernel). x: [N, C*kh*kw, L]."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cols = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :,
                         i * dh:i * dh + nh * sh:sh,
                         j * dw:j * dw + nw * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold_c = int(c * shift_ratio)
    back = jnp.concatenate([x5[:, 1:, :fold_c],
                            jnp.zeros_like(x5[:, :1, :fold_c])], axis=1)
    fwd = jnp.concatenate([jnp.zeros_like(x5[:, :1, fold_c:2 * fold_c]),
                           x5[:, :-1, fold_c:2 * fold_c]], axis=1)
    keep = x5[:, :, 2 * fold_c:]
    out = jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


def maxout(x, groups, axis=1):
    axis = axis % x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    p = [int(v) for v in paddings]  # [l, r, t, b, f, bk] (W, H, D order)
    if data_format == "NCDHW":
        cfg = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    else:
        cfg = [(0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]), (0, 0)]
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


def _ceil_extra(spatial, k, s, p, ceil_mode):
    """Extra high-side padding so output size rounds up (phi ceil_mode).
    reduce_window pads with the init value, so max/sum stay correct."""
    if not ceil_mode:
        return [0] * len(k)
    extra = []
    for sp, ki, si, pi in zip(spatial, k, s, p):
        out = -(-(sp + 2 * pi - ki) // si) + 1    # ceil
        extra.append(max((out - 1) * si + ki - (sp + 2 * pi), 0))
    return extra


def _pool_nd(x, k, s, p, reducer, init, ceil_mode=False):
    dims = (1, 1) + k
    strides = (1, 1) + s
    extra = _ceil_extra(x.shape[2:], k, s, p, ceil_mode)
    pads = [(0, 0), (0, 0)] + [(pi, pi + e) for pi, e in zip(p, extra)]
    # init stays a PYTHON scalar: jax's differentiable max-pool path
    # pattern-matches the -inf init value, and an abstract array breaks it
    return lax.reduce_window(x, init, reducer, dims, strides, pads)


def pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCDHW", pooling_type="max"):
    k = _pair(kernel_size, 3)
    s = _pair(stride if stride is not None else kernel_size, 3)
    p = _pair(padding, 3)
    if pooling_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            int(jnp.iinfo(x.dtype).min)
        return _pool_nd(x, k, s, p, lax.max, init, ceil_mode)
    ones_ = jnp.ones_like(x)
    summed = _pool_nd(x, k, s, p, lax.add, 0.0, ceil_mode)
    if exclusive:
        cnt = _pool_nd(ones_, k, s, p, lax.add, 0.0, ceil_mode)
    else:
        cnt = float(np.prod(k))
    return summed / cnt


max_pool3d = lambda x, kernel_size, stride=None, padding=0, \
    ceil_mode=False, data_format="NCDHW": pool3d(
        x, kernel_size, stride, padding, ceil_mode,
        data_format=data_format, pooling_type="max")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    return pool3d(x, kernel_size, stride, padding, ceil_mode, exclusive,
                  data_format, pooling_type="avg")


def _pool_with_index(x, k, s, p, spatial, ceil_mode=False):
    """Shared max-pool-with-argmax: extract windows, max + flat argmax."""
    n, c = x.shape[:2]
    patches = []
    idx_patches = []
    flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    extra = _ceil_extra(spatial, k, s, p, ceil_mode)
    pads = [(0, 0), (0, 0)] + [(pi, pi + e) for pi, e in zip(p, extra)]
    xp = jnp.pad(x, pads, constant_values=-jnp.inf)
    ip = jnp.pad(flat_idx, [(pi, pi + e) for pi, e in zip(p, extra)],
                 constant_values=-1)
    out_sp = [(sp + 2 * pi + e - ki) // si + 1
              for sp, pi, e, ki, si in zip(spatial, p, extra, k, s)]
    for offs in np.ndindex(*k):
        sl = tuple(
            builtins.slice(o, o + (osp - 1) * si + 1, si)
            for o, osp, si in zip(offs, out_sp, s))
        patches.append(xp[(builtins.slice(None),) * 2 + sl])
        idx_patches.append(ip[sl])
    stacked = jnp.stack(patches)          # [K, N, C, *out]
    sidx = jnp.stack(idx_patches)         # [K, *out]
    arg = jnp.argmax(stacked, axis=0)     # [N, C, *out]
    out = jnp.max(stacked, axis=0)
    sidx_b = jnp.broadcast_to(
        sidx[(builtins.slice(None), None, None)], stacked.shape)
    indices = jnp.take_along_axis(sidx_b, arg[None], axis=0)[0]
    return out, indices.astype(jnp.int32)


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    k = _pair(kernel_size)
    s = _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)
    return _pool_with_index(x, k, s, p, x.shape[2:], ceil_mode)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    k = _pair(kernel_size, 3)
    s = _pair(stride if stride is not None else kernel_size, 3)
    p = _pair(padding, 3)
    return _pool_with_index(x, k, s, p, x.shape[2:], ceil_mode)


def lp_pool2d(x, kernel_size, stride=None, padding=0, norm_type=2.0,
              ceil_mode=False, data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride if stride is not None else kernel_size)
    p = _pair(padding)
    xf = jnp.abs(x.astype(jnp.float32)) ** norm_type
    summed = _pool_nd(xf, k, s, p, lax.add, 0.0, ceil_mode)
    return (summed ** (1.0 / norm_type)).astype(x.dtype)


def nms(x, threshold=1.0):
    """Hard NMS over [N,4] boxes (sorted by caller) — dynamic output;
    eager-only like the reference's masked ops. Returns keep indices."""
    boxes = np.asarray(x, np.float32)
    n = boxes.shape[0]
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in range(n):
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[i + 1:])
        yy1 = np.maximum(y1[i], y1[i + 1:])
        xx2 = np.minimum(x2[i], x2[i + 1:])
        yy2 = np.minimum(y2[i], y2[i + 1:])
        inter = (np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0))
        iou = inter / np.maximum(areas[i] + areas[i + 1:] - inter, 1e-10)
        suppressed[i + 1:] |= iou > threshold
    return jnp.asarray(np.asarray(keep, np.int64))


def gather_tree(ids, parents):
    """Beam-search ancestry walk (phi GatherTreeKernel).
    ids/parents: [T, B, W]."""
    T = ids.shape[0]

    def body(carry, t):
        beams = carry                   # [B, W] current beam per slot
        got = jnp.take_along_axis(ids[t], beams, axis=-1)
        nxt = jnp.take_along_axis(parents[t], beams, axis=-1)
        return nxt, got

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, out = lax.scan(body, init, jnp.arange(T - 1, -1, -1))
    return jnp.flip(out, axis=0)


# ======================================================== conv extensions


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    """data_format selects the activation layout (NCDHW or NDHWC — the
    latter is what TPUs natively tile); the weight stays OIDHW in both,
    matching the reference's filter storage (same contract as conv2d)."""
    if data_format not in ("NCDHW", "NDHWC"):
        raise ValueError(f"conv3d: unsupported data_format {data_format!r}")
    s, d = _pair(stride, 3), _pair(dilation, 3)
    p = _pair(padding, 3)
    pad = [(pi, pi) for pi in p]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    (data_format, "OIDHW", data_format))
    out = lax.conv_general_dilated(x, weight, window_strides=s, padding=pad,
                                   rhs_dilation=d, dimension_numbers=dn,
                                   feature_group_count=groups)
    if bias is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
        out = out + bias.reshape(shape)
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    if data_format != "NCDHW":
        raise ValueError(
            f"conv3d_transpose: data_format={data_format!r} has no "
            "TPU-native lowering here — pass NCDHW and transpose the "
            "activations around the call (one cheap XLA relayout; the MXU "
            "tiles either layout equally)")
    s, d = _pair(stride, 3), _pair(dilation, 3)
    p = _pair(padding, 3)
    op = _pair(output_padding, 3)
    # weight layout IODHW (paddle stores [in, out/groups, kd, kh, kw])
    kd, kh, kw = weight.shape[2:]
    pad = [(d[i] * (ksz - 1) - p[i], d[i] * (ksz - 1) - p[i] + op[i])
           for i, ksz in enumerate((kd, kh, kw))]
    w = jnp.flip(weight, axis=(2, 3, 4))
    if groups > 1:
        i_, og = w.shape[0], w.shape[1]
        w = w.reshape(groups, i_ // groups, og, kd, kh, kw)
        w = jnp.moveaxis(w, 2, 1).reshape(groups * og, i_ // groups,
                                          kd, kh, kw)
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1, 1), padding=pad, lhs_dilation=s,
        rhs_dilation=d, dimension_numbers=dn, feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1, 1)
    return out


def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     groups=None, data_format="NCHW"):
    from paddle_tpu.ops.impl import conv2d

    return conv2d(x, weight, bias, stride, padding, dilation,
                  groups=x.shape[1], data_format=data_format)


def depthwise_conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                               output_padding=0, dilation=1, groups=None,
                               data_format="NCHW"):
    from paddle_tpu.ops.impl import conv2d_transpose

    return conv2d_transpose(x, weight, bias, stride, padding,
                            output_padding, dilation, groups=x.shape[1])


# ================================================= interp aliases / bilinear


def _resize(x, spatial, method, align_corners=False):
    spatial = tuple(int(v) for v in spatial)
    if not align_corners:
        return jax.image.resize(x, x.shape[:2] + spatial, method=method)
    if method == "cubic":
        raise NotImplementedError(
            "bicubic_interp with align_corners=True is not supported")
    # corner-aligned: sample at coords i*(in-1)/(out-1) per spatial axis
    out = x
    for ax, osz in enumerate(spatial):
        isz = out.shape[2 + ax]
        if osz == isz:
            continue
        coords = (jnp.arange(osz) * (isz - 1) / max(osz - 1, 1)
                  if osz > 1 else jnp.zeros(1))
        if method == "nearest":
            gathered = jnp.take(out, jnp.round(coords).astype(jnp.int32),
                                axis=2 + ax)
        else:
            lo = jnp.clip(jnp.floor(coords).astype(jnp.int32), 0, isz - 1)
            hi = jnp.clip(lo + 1, 0, isz - 1)
            wgt = (coords - lo).reshape(
                (1,) * (2 + ax) + (-1,) + (1,) * (out.ndim - 3 - ax))
            gathered = (jnp.take(out, lo, axis=2 + ax) * (1 - wgt)
                        + jnp.take(out, hi, axis=2 + ax) * wgt)
        out = gathered.astype(x.dtype)
    return out


def bilinear_interp(x, out_h, out_w, align_corners=False):
    return _resize(x, (out_h, out_w), "linear", align_corners)


def nearest_interp(x, out_h, out_w, align_corners=False):
    return _resize(x, (out_h, out_w), "nearest", align_corners)


def bicubic_interp(x, out_h, out_w, align_corners=False):
    return _resize(x, (out_h, out_w), "cubic", align_corners)


def linear_interp(x, out_w, align_corners=False):
    return _resize(x, (out_w,), "linear", align_corners)


def trilinear_interp(x, out_d, out_h, out_w, align_corners=False):
    return _resize(x, (out_d, out_h, out_w), "linear", align_corners)


def bilinear(x, y, weight, bias=None):
    """Bilinear tensor product: out[n,k] = x[n,i] W[k,i,j] y[n,j]."""
    out = jnp.einsum("ni,kij,nj->nk", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


# ===================================================== final-mile reference ops


def accuracy(x, indices, label):
    """(accuracy, correct, total) per phi AccuracyKernel: x = topk probs,
    indices = topk indices [N, k], label [N, 1]."""
    correct_k = (indices == label).any(axis=-1)
    correct = jnp.sum(correct_k.astype(jnp.int32))
    total = jnp.asarray(x.shape[0], jnp.int32)
    return (correct / total).astype(jnp.float32), correct, total


def auc(predict, label, num_thresholds=4095):
    """Batch ROC-AUC via thresholded TP/FP accumulation (phi AucKernel
    single-batch form)."""
    pos_prob = predict[:, 1] if predict.ndim == 2 else predict
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds + 1)
    lbl = label.reshape(-1).astype(bool)
    above = pos_prob.reshape(-1)[None, :] >= thresholds[:, None]
    tp = jnp.sum(above & lbl[None, :], axis=1).astype(jnp.float64)
    fp = jnp.sum(above & ~lbl[None, :], axis=1).astype(jnp.float64)
    tpr = tp / jnp.maximum(tp[0], 1)
    fpr = fp / jnp.maximum(fp[0], 1)
    return jnp.trapezoid(tpr[::-1], fpr[::-1]).astype(jnp.float32)


def affine_channel(x, scale, bias, data_format="NCHW"):
    shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
    return x * scale.reshape(shape) + bias.reshape(shape)


def conv2d_transpose_bias(x, weight, bias=None, stride=1, padding=0,
                          output_padding=0, dilation=1, groups=1):
    from paddle_tpu.ops.impl import conv2d_transpose

    return conv2d_transpose(x, weight, bias, stride, padding,
                            output_padding, dilation, groups)


def fft_c2c(x, axes=None, normalization="backward", forward=True):
    ax = tuple(axes) if axes is not None else None
    f = jnp.fft.fftn if forward else jnp.fft.ifftn
    return f(x, axes=ax, norm=normalization)


def fft_r2c(x, axes=None, normalization="backward", forward=True,
            onesided=True):
    ax = tuple(axes) if axes is not None else None
    return (jnp.fft.rfftn if onesided else jnp.fft.fftn)(
        x, axes=ax, norm=normalization)


def fft_c2r(x, axes=None, normalization="backward", forward=False,
            last_dim_size=0):
    ax = tuple(axes) if axes is not None else None
    n = None if not last_dim_size else int(last_dim_size)
    if ax is not None and n is not None:
        return jnp.fft.irfftn(x, s=(n,), axes=(ax[-1],), norm=normalization)
    return jnp.fft.irfftn(x, axes=ax, norm=normalization)


def _fractional_starts(in_sz, out_sz, u):
    alpha = (in_sz - 1) / out_sz if out_sz > 1 else 1.0
    idx = jnp.floor(alpha * (jnp.arange(out_sz) + u)).astype(jnp.int32)
    return jnp.clip(idx, 0, in_sz - 1)


def _fractional_pool(x, out_sizes, random_u):
    """Variable-window max pool via per-cell masks over the spatial dims.
    Returns (out, flat argmax indices)."""
    spatial = x.shape[2:]
    masks = []
    for sz, osz in zip(spatial, out_sizes):
        st = _fractional_starts(sz, osz, random_u)
        en = jnp.append(st[1:], sz)
        i = jnp.arange(sz)
        masks.append((i[None, :] >= st[:, None])
                     & (i[None, :] < en[:, None]))     # [o, in]
    nd = len(spatial)
    # outer product of [o_i, in_i] masks -> [o1..ok, in1..ink]
    m = masks[0]
    o_dims = [masks[0].shape[0]]
    in_dims = [masks[0].shape[1]]
    for mm in masks[1:]:
        m = (m.reshape(tuple(o_dims) + (1,) + tuple(in_dims) + (1,))
             & mm.reshape((1,) * len(o_dims) + (mm.shape[0],)
                          + (1,) * len(in_dims) + (mm.shape[1],)))
        # reorder to [o1..ok, in1..ink]
        perm = (list(range(len(o_dims))) + [len(o_dims)]
                + list(range(len(o_dims) + 1,
                             len(o_dims) + 1 + len(in_dims)))
                + [len(o_dims) + 1 + len(in_dims)])
        m = m.transpose(perm)
        o_dims.append(mm.shape[0])
        in_dims.append(mm.shape[1])
    xb = x.reshape(x.shape[:2] + (1,) * nd + spatial)
    mb = m[(None, None)]
    masked = jnp.where(mb, xb, -jnp.inf)
    flat = masked.reshape(x.shape[:2] + tuple(o_dims) + (-1,))
    out = jnp.max(flat, axis=-1)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    return out, idx


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=0.5,
                          return_mask=False):
    out_sizes = (output_size if isinstance(output_size, (list, tuple))
                 else (output_size,) * 2)
    out, idx = _fractional_pool(x, out_sizes, random_u)
    return (out, idx) if return_mask else out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=0.5,
                          return_mask=False):
    out_sizes = (output_size if isinstance(output_size, (list, tuple))
                 else (output_size,) * 3)
    out, idx = _fractional_pool(x, out_sizes, random_u)
    return (out, idx) if return_mask else out


def frame(x, frame_length, hop_length, axis=-1):
    """[..., seq] -> [..., frame_length, num_frames] (axis=-1; phi
    FrameKernel layout)."""
    n = x.shape[axis]
    num = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[:, None]
           + hop_length * jnp.arange(num)[None, :])  # [fl, num]
    if axis in (-1, x.ndim - 1):
        return x[..., idx]
    if axis in (0, -x.ndim):
        return x[idx.T.reshape(-1)].reshape((num, frame_length)
                                            + x.shape[1:]).swapaxes(0, 1)
    raise NotImplementedError("frame: axis must be first or last")


def overlap_add(x, hop_length, axis=-1):
    """Inverse of frame: x [..., frame_length, num_frames] (axis=-1)."""
    if axis != -1:
        raise NotImplementedError("overlap_add: axis=-1 only")
    fl, nf = x.shape[-2], x.shape[-1]
    out_len = (nf - 1) * hop_length + fl
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    for f in range(nf):
        out = out.at[..., f * hop_length:f * hop_length + fl].add(
            x[..., :, f])
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = jnp.ones(win_length, x.dtype) if window is None else window
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        win = jnp.pad(win, (lp, n_fft - win_length - lp))
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    frames = frame(x, n_fft, hop_length, axis=-1)   # [..., n_fft, num]
    spec = (jnp.fft.rfft if onesided else jnp.fft.fft)(
        frames * win[:, None], axis=-2)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    return spec


def full_(x, shape=None, fill_value=0.0, dtype=None):
    return jnp.full_like(x, fill_value)


def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x, y)


def hsigmoid_loss(x, label, w, bias=None, num_classes=2):
    """Hierarchical sigmoid over the default complete binary tree (phi
    HSigmoidLossKernel default-path mode). Returns (out, pre_out, w_out)."""
    code_length = max(int(math.ceil(math.log2(max(num_classes, 2)))), 1)
    n = x.shape[0]
    codes = []
    paths = []
    lbl = label.reshape(-1).astype(jnp.int32) + num_classes - 1
    cur = lbl
    for _ in range(code_length):
        parent = (cur - 1) // 2
        codes.append((cur % 2 == 0).astype(jnp.float32))  # right child -> 1
        paths.append(parent)
        cur = parent
    path = jnp.stack(paths, axis=1)          # [N, L] internal node ids
    code = jnp.stack(codes, axis=1)          # [N, L]
    wp = w[path]                             # [N, L, D]
    pre = jnp.einsum("nld,nd->nl", wp, x)
    if bias is not None:
        pre = pre + bias.reshape(-1)[path]
    valid = (path >= 0) & (path < w.shape[0])
    ce = jnp.maximum(pre, 0) - pre * code + jnp.log1p(jnp.exp(-jnp.abs(pre)))
    out = jnp.sum(jnp.where(valid, ce, 0.0), axis=1, keepdims=True)
    return out, pre, w


def matrix_rank_tol(x, tol_tensor, use_default_tol=True, hermitian=False):
    return jnp.linalg.matrix_rank(x, tol=tol_tensor)


def matrix_rank_atol_rtol(x, atol=None, rtol=None, hermitian=False):
    s = jnp.linalg.svd(x, compute_uv=False)
    a = 0.0 if atol is None else atol
    r = (jnp.finfo(x.dtype).eps * max(x.shape[-2:])) if rtol is None else rtol
    tol = jnp.maximum(jnp.asarray(a), r * s[..., 0])
    return jnp.sum(s > tol[..., None], axis=-1)


def pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCHW", pooling_type="max",
           global_pooling=False, adaptive=False):
    from paddle_tpu.ops.impl import avg_pool2d, max_pool2d

    if global_pooling:
        kernel_size = x.shape[2:]
        stride, padding = kernel_size, 0
    if pooling_type == "max":
        return max_pool2d(x, kernel_size, stride, padding, ceil_mode,
                          data_format)
    return avg_pool2d(x, kernel_size, stride, padding, ceil_mode,
                      exclusive, data_format)


def reduce_as(x, target):
    """Sum-reduce x down to target's shape (phi ReduceAsKernel)."""
    extra = x.ndim - target.ndim
    if extra:
        x = jnp.sum(x, axis=tuple(range(extra)))
    axes = tuple(i for i, (a, b) in enumerate(zip(x.shape, target.shape))
                 if a != b and b == 1)
    if axes:
        x = jnp.sum(x, axis=axes, keepdims=True)
    return x


def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    w = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
    for _ in range(max(power_iters, 0)):
        v = w.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = w @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ w @ v
    return weight / sigma


def unpool(x, indices, kernel_size=None, stride=None, padding=0,
           output_size=None, data_format="NCHW"):
    """Max-unpool2d: scatter pooled values back at `indices` (flat H*W)."""
    n, c, h, w = x.shape
    if output_size is not None:
        oh, ow = int(output_size[-2]), int(output_size[-1])
    else:
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else \
            (kernel_size,) * 2
        s = stride or k
        s = s if isinstance(s, (list, tuple)) else (s,) * 2
        oh, ow = h * s[0], w * s[1]
    out = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, indices.reshape(n, c, -1), x.reshape(n, c, -1))
    return out.reshape(n, c, oh, ow)


def unpool3d(x, indices, kernel_size=None, stride=None, padding=0,
             output_size=None, data_format="NCDHW"):
    n, c, d, h, w = x.shape
    if output_size is not None:
        od, oh, ow = (int(v) for v in output_size[-3:])
    else:
        k = kernel_size if isinstance(kernel_size, (list, tuple)) else \
            (kernel_size,) * 3
        s = stride or k
        s = s if isinstance(s, (list, tuple)) else (s,) * 3
        od, oh, ow = d * s[0], h * s[1], w * s[2]
    out = jnp.zeros((n, c, od * oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, indices.reshape(n, c, -1), x.reshape(n, c, -1))
    return out.reshape(n, c, od, oh, ow)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    if data_format != "NCL":
        raise ValueError(
            f"conv1d_transpose: data_format={data_format!r} has no "
            "TPU-native lowering here — pass NCL and transpose the "
            "activations around the call (one cheap XLA relayout)")
    from paddle_tpu.ops.impl import conv2d_transpose

    s = stride if isinstance(stride, int) else stride[0]
    p = padding if isinstance(padding, int) else padding[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    op = output_padding if isinstance(output_padding, int) \
        else output_padding[0]
    out = conv2d_transpose(x[:, :, None, :], weight[:, :, None, :], bias,
                           stride=(1, s), padding=(0, p),
                           output_padding=(0, op), dilation=(1, d),
                           groups=groups)
    return out[:, :, 0, :]


def warpctc(log_probs, labels, input_lengths, label_lengths, blank=0,
            reduction="mean"):
    """CTC loss — log-semiring alpha recursion (reference: the warpctc
    kernel behind nn/functional/loss.py ctc_loss). log_probs: [T, B, C]
    log-softmax outputs; labels: [B, S]. One lax.scan over time with a
    static [B, 2S+1] lattice — jittable, differentiable via autodiff."""
    # reference warpctc applies softmax internally to unscaled logits;
    # log_softmax is idempotent for already-normalized input
    lp = jax.nn.log_softmax(jnp.asarray(log_probs).astype(jnp.float32), -1)
    lab = jnp.asarray(labels).astype(jnp.int32)
    in_len = jnp.asarray(input_lengths).astype(jnp.int32)
    lab_len = jnp.asarray(label_lengths).astype(jnp.int32)
    if lp.ndim == 2:
        lp = lp[:, None]
        lab = lab[None] if lab.ndim == 1 else lab
    T, B, C = lp.shape
    S = lab.shape[1]
    NEG = -1e30

    # extended label sequence: blank, l1, blank, l2, ... blank  [B, 2S+1]
    ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    ext_valid = jnp.arange(2 * S + 1)[None, :] < (2 * lab_len + 1)[:, None]
    same_as_prev = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)
    can_skip = (jnp.arange(2 * S + 1)[None, :] % 2 == 1) & ~same_as_prev

    alpha0 = jnp.full((B, 2 * S + 1), NEG)
    alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), ext[:, 0]])
    has1 = (2 * lab_len + 1) > 1
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(has1, lp[0, jnp.arange(B), ext[:, 1]], NEG))

    def step(alpha, lp_t):
        stay = alpha
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)     # [B, 2S+1]
        new = jnp.where(ext_valid, merged + emit, NEG)
        return new, new

    _, alphas = lax.scan(step, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T,B,2S+1]
    t_idx = jnp.clip(in_len - 1, 0, T - 1)
    a_T = alphas[t_idx, jnp.arange(B)]                        # [B, 2S+1]
    sL = 2 * lab_len
    last_blank = jnp.take_along_axis(a_T, sL[:, None], axis=1)[:, 0]
    last_label = jnp.where(
        lab_len > 0,
        jnp.take_along_axis(a_T, jnp.maximum(sL - 1, 0)[:, None],
                            axis=1)[:, 0],
        -1e30)  # empty label: only the all-blank path exists
    nll = -jnp.logaddexp(last_blank, last_label)
    if reduction == "mean":
        # warpctc convention: per-sample loss / label_length, batch mean
        return jnp.mean(nll / jnp.maximum(lab_len.astype(jnp.float32),
                                          1.0))
    if reduction == "sum":
        return jnp.sum(nll)
    return nll

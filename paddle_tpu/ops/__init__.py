from paddle_tpu.ops.registry import C_OPS, OPS, dispatch  # noqa: F401

"""Op implementations: pure, jittable functions over jax arrays.

This is the analogue of the reference kernel library (paddle/phi/kernels/ —
~600 op kernels across cpu/gpu/xpu backends). On TPU there is exactly one
backend: every op lowers to XLA HLO (jax.numpy / jax.lax / jax.nn), which
XLA fuses and tiles onto the MXU/VPU; hand-written Pallas kernels slot in only
where fusion can't express the op (see paddle_tpu/ops/pallas/). Shape/dtype
inference (the reference's paddle/phi/infermeta/) comes free from jax's
abstract evaluation.

Conventions:
  - functions take jax arrays positionally + python attrs as keywords,
    return a jax array or tuple of arrays
  - NCHW layout for conv/pool (paddle default data_format="NCHW")
  - names match the op names registered in ops.yaml
"""

from __future__ import annotations

import math
from functools import partial

from jax.dtypes import canonicalize_dtype as _canon

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ============================================================ element-wise math


def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def remainder(x, y):
    return jnp.remainder(x, y)


def pow(x, y):  # noqa: A001
    return jnp.power(x, y)


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def fmax(x, y):
    return jnp.fmax(x, y)


def fmin(x, y):
    return jnp.fmin(x, y)


def atan2(x, y):
    return jnp.arctan2(x, y)


def abs(x):  # noqa: A001
    return jnp.abs(x)


def neg(x):
    return jnp.negative(x)


def exp(x):
    return jnp.exp(x)


def expm1(x):
    return jnp.expm1(x)


def log(x):
    return jnp.log(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def log1p(x):
    return jnp.log1p(x)


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return lax.rsqrt(x)


def square(x):
    return jnp.square(x)


def reciprocal(x):
    return jnp.reciprocal(x)


def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def tan(x):
    return jnp.tan(x)


def asin(x):
    return jnp.arcsin(x)


def acos(x):
    return jnp.arccos(x)


def atan(x):
    return jnp.arctan(x)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def tanh(x):
    return jnp.tanh(x)


def asinh(x):
    return jnp.arcsinh(x)


def acosh(x):
    return jnp.arccosh(x)


def atanh(x):
    return jnp.arctanh(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def round(x):  # noqa: A001
    return jnp.round(x)


def trunc(x):
    return jnp.trunc(x)


def frac(x):
    return x - jnp.trunc(x)


def sign(x):
    return jnp.sign(x)


def erf(x):
    return jax.scipy.special.erf(x)


def erfinv(x):
    return jax.scipy.special.erfinv(x)


def digamma(x):
    return jax.scipy.special.digamma(x)


def lgamma(x):
    return jax.scipy.special.gammaln(x)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


def isnan(x):
    return jnp.isnan(x)


def isinf(x):
    return jnp.isinf(x)


def isfinite(x):
    return jnp.isfinite(x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def lerp(x, y, weight):
    return x + weight * (y - x)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


# ============================================================ comparison/logical


def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def logical_not(x):
    return jnp.logical_not(x)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


# ============================================================ matmul / linalg


def matmul(x, y, transpose_x=False, transpose_y=False):
    """Reference: phi MatmulKernel. On TPU this is the MXU op — keep operands
    large/batched; bf16 inputs hit the systolic array natively."""
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def norm(x, p=2, axis=None, keepdim=False):
    if p == "fro" or p == 2:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


def t(x):
    return jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x


def outer(x, y):
    return jnp.outer(x, y)


def bmm(x, y):
    return jnp.matmul(x, y)


def mv(x, vec):
    return jnp.matmul(x, vec)


def cholesky(x, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
    )


def inverse(x):
    return jnp.linalg.inv(x)


# ============================================================ reductions


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(_canon(jnp.dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(_canon(jnp.dtype(dtype)))


def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


# ============================================================ manipulation


def reshape(x, shape):
    return jnp.reshape(x, shape)


def transpose(x, perm):
    return jnp.transpose(x, perm)


def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


def concat(xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def stack(xs, axis=0):
    return jnp.stack(xs, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = list(num_or_sections)
    # paddle allows one -1 section
    if -1 in sections:
        known = builtins_sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = x.shape[axis] - known
    idx = np.cumsum(sections)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, chunks, axis=axis))


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape((1,))
    s = start_axis % nd
    e = stop_axis % nd
    shape = x.shape[:s] + (-1,) + x.shape[e + 1 :]
    return jnp.reshape(x, shape)


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def expand(x, shape):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def where(condition, x, y):
    return jnp.where(condition, x, y)


def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    dims = list(range(x.ndim))
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in dims]) for d, s in enumerate(indices.shape)]
    idx[axis] = indices
    idx = [jnp.broadcast_to(i, indices.shape) for i in idx]
    vals = jnp.broadcast_to(values, indices.shape)
    at = x.at[tuple(idx)]
    if reduce == "add":
        return at.add(vals)
    if reduce == "multiply" or reduce == "mul":
        return at.multiply(vals)
    raise ValueError(f"unsupported reduce {reduce}")


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def masked_select(x, mask):
    # dynamic output shape — not jittable; eager-only op (same caveat as
    # reference's masked_select which is shape-dynamic)
    return x[mask]


def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    if len(pad) == 2 * x.ndim:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(x.ndim)]
    else:
        # paddle nn.functional.pad pads trailing spatial dims, given as
        # [l, r, (t, b, ...)] for the last len(pad)//2 dims (NCHW)
        n = len(pad) // 2
        width = [(0, 0)] * (x.ndim - n)
        for i in range(n):
            width.append((pad[2 * (n - 1 - i)], pad[2 * (n - 1 - i) + 1]))
    if mode == "constant":
        return jnp.pad(x, width, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, width, mode=jmode)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def diag(x, offset=0):
    return jnp.diag(x, k=offset)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def unbind(x, axis=0):
    return tuple(jnp.moveaxis(x, axis, 0))


def unstack(x, axis=0, num=None):
    return tuple(jnp.moveaxis(x, axis, 0))


def as_strided_slice(x, axes, starts, ends, strides=None):
    idx = [slice(None)] * x.ndim
    strides = strides or [1] * len(axes)
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    return x[tuple(idx)]


def one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes)


def kron(x, y):
    return jnp.kron(x, y)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, values, side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else _canon(jnp.int64))


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


def cast(x, dtype):
    return x.astype(dtype)


# ============================================================ sort / search


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    if not largest:
        vals, idx = lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    else:
        vals, idx = lax.top_k(jnp.moveaxis(x, axis, -1), k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(_canon(jnp.int64))


def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis)
    if descending:
        out = jnp.flip(out, axis=axis)
    return out.astype(_canon(jnp.int64))


def nonzero(x):
    # dynamic shape — eager-only (reference: NonZeroKernel, also dynamic)
    return jnp.stack(jnp.nonzero(x), axis=1).astype(_canon(jnp.int64))


def unique(x, return_index=False, return_inverse=False, return_counts=False):
    # dynamic shape — eager-only
    res = jnp.unique(
        x, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts,
    )
    return res


# ============================================================ activations


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def silu(x):
    return jax.nn.silu(x)


def swish(x):
    return jax.nn.silu(x)


def mish(x):
    return jax.nn.mish(x)


def hardswish(x):
    return jax.nn.hard_swish(x)


def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha)


def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(beta * x > threshold, x, (1.0 / beta) * jnp.log1p(jnp.exp(beta * x)))


def softsign(x):
    return jax.nn.soft_sign(x)


def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def tanhshrink(x):
    return x - jnp.tanh(x)


def prelu(x, weight):
    return jnp.where(x >= 0, x, weight * x)


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def swiglu(x, y=None):
    """Reference: fused swiglu (python/paddle/incubate/nn/functional/swiglu)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


# ============================================================ nn core ops


def linear(x, weight, bias=None):
    """Reference: phi FcKernel / matmul+add. weight layout [in, out] (paddle
    convention, nn/layer/common.py Linear)."""
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def embedding(x, weight, padding_idx=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def dropout(x, key, p=0.5, training=True, mode="upscale_in_train"):
    if p == 0.0:
        return x
    keep = 1.0 - p
    if not training:
        # downscale_in_infer scales activations by keep-prob at inference
        # (reference: phi DropoutKernel, python nn/functional/common.py)
        if mode == "downscale_in_infer":
            return (x * keep).astype(x.dtype)
        return x
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def layer_norm(x, weight=None, bias=None, epsilon=1e-5, begin_norm_axis=-1):
    """Reference: phi LayerNormKernel. Normalizes over trailing dims starting
    at begin_norm_axis (paddle semantics); weight/bias broadcast over them."""
    if begin_norm_axis < 0:
        begin_norm_axis += x.ndim
    axes = tuple(range(begin_norm_axis, x.ndim))
    # compute statistics in fp32 for bf16 stability (TPU practice)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(x.shape[begin_norm_axis:])
    if bias is not None:
        out = out + bias.reshape(x.shape[begin_norm_axis:])
    return out


def rms_norm(x, weight=None, epsilon=1e-6):
    """Reference: fused_rms_norm (paddle/phi/kernels/fusion/). XLA fuses this
    chain into one kernel on TPU; no custom kernel needed."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def batch_norm(
    x, running_mean, running_var, weight=None, bias=None,
    training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
):
    """Returns (out, new_mean, new_var). Reference: phi BatchNormKernel."""
    if data_format == "NCHW":
        axes = (0,) + tuple(range(2, x.ndim))
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    else:
        axes = tuple(range(x.ndim - 1))
        shape = [1] * (x.ndim - 1) + [x.shape[-1]]
    if training:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        # running_var uses the BIASED batch variance (divide by N, no Bessel
        # correction), matching the reference phi kernel
        # (paddle/phi/kernels/cpu/batch_norm_kernel.cc:128-157) — the torch
        # convention (unbiased) would make eval outputs / ported checkpoints
        # diverge from reference-trained behavior.
        new_mean = momentum * running_mean + (1 - momentum) * mean
        new_var = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    out = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape).astype(jnp.float32) + epsilon).astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out.astype(x.dtype), new_mean, new_var


def group_norm(x, weight=None, bias=None, epsilon=1e-5, groups=1, data_format="NCHW"):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    g = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    out = ((g - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


# ============================================================ conv / pool


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """Reference: phi Conv2dKernel (gpudnn). Lowers to XLA conv_general_dilated
    which maps onto the MXU. data_format selects the activation layout
    (NCHW or NHWC — the latter is what TPUs natively tile); the weight
    stays OIHW in both, matching the reference's filter storage."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"conv2d: unsupported data_format {data_format!r}")
    stride = _pair(stride)
    dilation = _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()  # "SAME"/"VALID"
    else:
        p = _pair(padding)
        if len(p) == 4:
            pad = [(p[0], p[1]), (p[2], p[3])]
        else:
            pad = [(p[0], p[0]), (p[1], p[1])]
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    (data_format, "OIHW", data_format))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + bias.reshape(shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1):
    x4 = x[:, :, None, :]
    w4 = weight[:, :, None, :]
    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    p = padding if isinstance(padding, int) else padding[0]
    out = conv2d(x4, w4, bias, stride=(1, s), padding=(0, p), dilation=(1, d),
                 groups=groups)
    return out[:, :, 0, :]


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1):
    stride = _pair(stride)
    dilation = _pair(dilation)
    p = _pair(padding)
    opad = _pair(output_padding)
    # weight layout IOHW (paddle conv_transpose stores [in, out/groups, kh, kw])
    kh, kw = weight.shape[2], weight.shape[3]
    pad = [
        (dilation[0] * (kh - 1) - p[0], dilation[0] * (kh - 1) - p[0] + opad[0]),
        (dilation[1] * (kw - 1) - p[1], dilation[1] * (kw - 1) - p[1] + opad[1]),
    ]
    w = jnp.flip(weight, axis=(2, 3))
    if groups > 1:
        i, og = w.shape[0], w.shape[1]
        w = w.reshape(groups, i // groups, og, kh, kw)
        w = jnp.moveaxis(w, 2, 1).reshape(groups * og, i // groups, kh, kw)
    else:
        w = jnp.swapaxes(w, 0, 1)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


def _pool_pads(x, k, s, p, ceil_mode):
    """Low/high spatial padding; ceil_mode adds extra high padding so the
    last partial window is included (reference: phi pooling infermeta)."""
    extra = [0, 0]
    if ceil_mode:
        for i, dim in enumerate((2, 3)):
            size = x.shape[dim] + 2 * p[i]
            rem = (size - k[i]) % s[i]
            if rem:
                extra[i] = s[i] - rem
    return [(0, 0), (0, 0), (p[0], p[0] + extra[0]), (p[1], p[1] + extra[1])]


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = _pool_pads(x, k, s, p, ceil_mode)
    if jnp.issubdtype(x.dtype, jnp.floating):
        init = -jnp.inf  # -inf init selects jax's differentiable max-pool path
    else:
        init = jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, dims, strides, pads)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCHW"):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = _pool_pads(x, k, s, p, ceil_mode)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if exclusive and (p[0] or p[1] or ceil_mode):
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return summed / counts
    return summed / (k[0] * k[1])


def adaptive_avg_pool2d(x, output_size):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    # paddle adaptive pooling: split into near-equal windows
    if h % oh == 0 and w % ow == 0:
        return jnp.mean(x.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))
    out = jax.image.resize(x, (n, c, oh, ow), method="linear")  # approx
    return out


def adaptive_max_pool2d(x, output_size):
    oh, ow = _pair(output_size)
    n, c, h, w = x.shape
    assert h % oh == 0 and w % ow == 0, "adaptive_max_pool2d needs divisible sizes"
    return jnp.max(x.reshape(n, c, oh, h // oh, ow, w // ow), axis=(3, 5))


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    n, c, h, w = x.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]
    return jax.image.resize(x, (n, c, size[0], size[1]), method=method)


def pixel_shuffle(x, upscale_factor):
    n, c, h, w = x.shape
    r = upscale_factor
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _pair(paddings)
    d = _pair(dilations)
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])])
    oh = (h + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s, padding=[(0, 0), (0, 0)],
        rhs_dilation=d, dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, k[0], k[1]), ("NCHW", "OIHW", "NCHW")),
    )
    return patches.reshape(n, c * k[0] * k[1], oh * ow)


# ============================================================ losses


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        return -jnp.sum(label * logp, axis=axis, keepdims=True)
    lab = label
    squeeze = False
    if lab.ndim == logits.ndim:
        lab = jnp.squeeze(lab, axis=axis)
        squeeze = True
    picked = jnp.take_along_axis(logp, jnp.expand_dims(jnp.maximum(lab, 0), axis), axis=axis)
    loss = -picked
    mask = jnp.expand_dims(lab == ignore_index, axis)
    loss = jnp.where(mask, 0.0, loss)
    return loss


def cross_entropy(logits, label, soft_label=False, axis=-1, ignore_index=-100,
                  reduction="mean", weight=None, label_smoothing=0.0):
    """Reference: python/paddle/nn/functional/loss.py cross_entropy."""
    num_classes = logits.shape[axis]
    if label_smoothing > 0.0 and not soft_label:
        onehot = jax.nn.one_hot(label, num_classes, dtype=logits.dtype)
        soft = onehot * (1 - label_smoothing) + label_smoothing / num_classes
        loss = softmax_with_cross_entropy(logits, soft, soft_label=True, axis=axis)
        valid = jnp.ones(loss.shape, dtype=logits.dtype)
    else:
        loss = softmax_with_cross_entropy(
            logits, label, soft_label=soft_label, axis=axis, ignore_index=ignore_index
        )
        if soft_label:
            valid = jnp.ones(loss.shape, dtype=logits.dtype)
        else:
            lab = label
            if lab.ndim == logits.ndim:
                lab = jnp.squeeze(lab, axis=axis)
            valid = jnp.expand_dims((lab != ignore_index).astype(logits.dtype), axis)
    if weight is not None and not soft_label:
        lab = label if label.ndim < logits.ndim else jnp.squeeze(label, axis=axis)
        w = jnp.take(weight, jnp.maximum(lab, 0))
        loss = loss * jnp.expand_dims(w, axis)
        valid = valid * jnp.expand_dims(w, axis)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-8)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def nll_loss(log_prob, label, weight=None, ignore_index=-100, reduction="mean"):
    picked = -jnp.take_along_axis(log_prob, jnp.expand_dims(jnp.maximum(label, 0), -1), axis=-1)
    picked = jnp.squeeze(picked, -1)
    valid = (label != ignore_index).astype(log_prob.dtype)
    if weight is not None:
        w = jnp.take(weight, jnp.maximum(label, 0)) * valid
    else:
        w = valid
    picked = picked * w
    if reduction == "mean":
        return jnp.sum(picked) / jnp.maximum(jnp.sum(w), 1e-8)
    if reduction == "sum":
        return jnp.sum(picked)
    return picked


def mse_loss(input, label, reduction="mean"):
    loss = jnp.square(input - label)
    return _reduce_loss(loss, reduction)


def l1_loss(input, label, reduction="mean"):
    loss = jnp.abs(input - label)
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    eps = 1e-12
    loss = -(label * jnp.log(input + eps) + (1 - label) * jnp.log(1 - input + eps))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
        )
    else:
        loss = (1 - label) * logit + max_val + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


def kl_div(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.clip(label, 1e-12, None)) - input)
    return _reduce_loss(loss, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot_ = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot_ / jnp.maximum(n1 * n2, eps)


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ============================================================ attention


def _flash_enabled() -> bool:
    """Flash dispatch gate (separate function so tests can patch it)."""
    from paddle_tpu.utils.flags import flag

    return flag("FLAGS_use_flash_attention") and jax.default_backend() == "tpu"


_SDPA_FALLBACK_WARNED: set = set()


def _warn_sdpa_fallback(q, k, mask_ok):
    """Warn once per shape when SDPA declines the flash kernel (VERDICT-r4
    Weak #9: a seq-500 batch quietly paying O(s^2) dense attention is a
    silent 10x perf cliff)."""
    key = (tuple(q.shape), tuple(k.shape), bool(mask_ok))
    if key in _SDPA_FALLBACK_WARNED:
        return
    _SDPA_FALLBACK_WARNED.add(key)
    import warnings

    reason = ("mask shape not broadcastable to [b, h, sq, sk]"
              if not mask_ok else
              "sequence/head dims don't tile (seq % 128, head dim % 8)")
    warnings.warn(
        f"scaled_dot_product_attention: q={tuple(q.shape)} "
        f"k={tuple(k.shape)} falls back to the O(s^2) XLA path — {reason}",
        stacklevel=3)


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None):
    """Reference: paddle.nn.functional.scaled_dot_product_attention /
    flash_attention (python/paddle/nn/functional/flash_attention.py:358).

    Layout [batch, seq, heads, head_dim] (paddle flash-attn convention).
    Computed at fp32 accumulation. When the shapes tile (d % 8 == 0,
    seq % 128 == 0) and no dropout is requested, dispatches to the Pallas
    flash kernel (paddle_tpu/ops/pallas/flash_attention.py) — including
    masked attention: broadcastable attn_masks ([b,1,1,sk] padding form,
    [b,1|h,sq,sk] dense form, bool or additive) are streamed tile-wise into
    the kernel, so ERNIE-style padded pretraining takes the flash path.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else (1.0 / math.sqrt(d))

    # flags are part of the per-op jit cache key (registry flags_version),
    # so this read is re-evaluated after any set_flags. TPU-only: on other
    # backends the interpret-mode kernel would be slower than the XLA path.
    if dropout_p == 0.0 and _flash_enabled():
        from paddle_tpu.ops.pallas.flash_attention import (
            _block_shapes_ok, flash_attention)

        mask_ok = attn_mask is None
        if attn_mask is not None:
            # shape-only classification (no value inspection — this runs
            # under tracing): any mask broadcastable to [b, 1|h, sq, sk]
            ms = tuple(attn_mask.shape)
            mask_ok = (len(ms) == 4 and ms[0] in (1, b)
                       and ms[1] in (1, h) and ms[2] in (1, sq)
                       and ms[3] in (1, sk))
        if mask_ok and _block_shapes_ok(q, k, 128, 128, v=v):
            return flash_attention(q, k, v, causal=is_causal, scale=scale,
                                   mask=attn_mask)
        if (mask_ok and d % 8 == 0 and sq == sk and sq >= 256
                and q.shape[:1] + q.shape[2:] == k.shape[:1] + k.shape[2:]
                and tuple(v.shape) == tuple(k.shape)):
            # seq not tile-aligned (e.g. ERNIE's 500-ish batches): pad to
            # the next 128 multiple and mask the padded keys — the kernel
            # at seq+pad beats the O(s^2) dense path it would otherwise
            # silently fall to (VERDICT-r4 Weak #9)
            sp = ((sq + 127) // 128) * 128
            pad = sp - sq
            qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
            kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
            vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
            if attn_mask is None:
                mp = jnp.where(
                    jnp.arange(sp)[None, None, None, :] < sk, 0.0,
                    -1e30).astype(jnp.float32)
            else:
                am = attn_mask
                if am.dtype == jnp.bool_:
                    am = jnp.where(am, 0.0, -1e30).astype(jnp.float32)
                mp = jnp.pad(am.astype(jnp.float32),
                             ((0, 0), (0, 0),
                              (0, sp - am.shape[2] if am.shape[2] > 1
                               else 0),
                              (0, sp - am.shape[3] if am.shape[3] > 1
                               else 0)),
                             constant_values=-1e30)
                if am.shape[3] == 1:   # broadcast kv dim: add pad mask
                    mp = mp + jnp.where(
                        jnp.arange(sp)[None, None, None, :] < sk, 0.0,
                        -1e30)
            out = flash_attention(qp, kp, vp, causal=is_causal,
                                  scale=scale, mask=mp)
            return out[:, :sq]
        _warn_sdpa_fallback(q, k, mask_ok)
    qT = jnp.swapaxes(q, 1, 2)  # b h s d
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qT, kT).astype(jnp.float32) * scale
    if is_causal:
        sk = kT.shape[2]
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal, scores, -1e30)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -1e30)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if is_causal or attn_mask is not None:
        # fully-hard-masked rows output exactly 0 (not a uniform average) —
        # same semantics as the Pallas kernel's masked-row guard, so the
        # result does not depend on which path dispatch picks
        row_live = jnp.any(scores > -5e29, axis=-1, keepdims=True)
        probs = jnp.where(row_live, probs, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vT)
    return jnp.swapaxes(out, 1, 2)


def flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q=None, max_seqlen_k=None, scale=None,
                        dropout=0.0, causal=False):
    """Varlen (packed/unpadded) flash attention. Reference:
    python/paddle/nn/functional/flash_attention.py:756 (flash_attn_unpadded
    over the varlen CUDA kernel, phi/kernels/gpu/flash_attn_kernel.cu).

    q/k/v: [total_tokens, heads, head_dim] — multiple sequences packed along
    dim 0; cu_seqlens_*: int32 [b+1] cumulative boundaries. TPU design: the
    boundaries lower onto per-token segment ids (searchsorted over the
    traced boundary values — O(total) memory, no dense mask), and the
    Pallas kernel masks where q_seg != k_seg. With `causal`, global causal
    ∧ same-segment equals per-sequence causal when q and k share a packing
    (the standard use). Tokens are padded to the 128-tile and sliced back.
    """
    tq, h, d = q.shape
    tk = k.shape[0]
    scale = scale if scale is not None else (1.0 / math.sqrt(d))
    if dropout:
        raise NotImplementedError(
            "flash_attn_unpadded: attention dropout is not implemented in "
            "the TPU flash kernel (reference applies it in-kernel); train "
            "with dropout=0.0")
    if causal and tq != tk:
        raise ValueError(
            "flash_attn_unpadded(causal=True) requires q and k to share a "
            f"packing (got {tq} vs {tk} total tokens): global causal over "
            "mismatched packings is not per-sequence causal")

    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    pad_q = (-tq) % 128
    pad_k = (-tk) % 128
    # padded positions land past cu_seqlens[-1] -> searchsorted gives b+1,
    # a segment no real token carries, so pads only ever attend pads
    seg_q = jnp.searchsorted(cu_seqlens_q.astype(jnp.int32),
                             jnp.arange(tq + pad_q, dtype=jnp.int32),
                             side="right").astype(jnp.int32)
    seg_k = jnp.searchsorted(cu_seqlens_k.astype(jnp.int32),
                             jnp.arange(tk + pad_k, dtype=jnp.int32),
                             side="right").astype(jnp.int32)
    pad3 = lambda t, p: jnp.pad(t, ((0, p), (0, 0), (0, 0)))
    out = flash_attention(
        pad3(q, pad_q)[None], pad3(k, pad_k)[None], pad3(v, pad_k)[None],
        causal=causal, scale=scale,
        segment_ids=(seg_q[None], seg_k[None]))
    return out[0, :tq]


def flash_attn(q, k, v, dropout=0.0, causal=False):
    """Reference flash_attn op (ops.yaml): the base dense form — same
    dispatch as scaled_dot_product_attention (Pallas kernel when shapes
    tile and the gate is open)."""
    return scaled_dot_product_attention(q, k, v, dropout_p=dropout,
                                        is_causal=causal)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False):
    """Packed [b, s, 3, h, d] form (reference flash_attn_qkvpacked)."""
    return flash_attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                      dropout=dropout, causal=causal)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False):
    """Packed varlen [total, 3, h, d] form (reference
    flash_attn_varlen_qkvpacked) — lowers onto flash_attn_unpadded's
    segment-id kernel path."""
    return flash_attn_unpadded(
        qkv[:, 0], qkv[:, 1], qkv[:, 2], cu_seqlens_q, cu_seqlens_k,
        max_seqlen_q=max_seqlen_q, max_seqlen_k=max_seqlen_k, scale=scale,
        dropout=dropout, causal=causal)


def flashmask_attention(q, k, v, startend_row_indices=None, dropout=0.0,
                        causal=False, window_size=None):
    """FlashMask column-sparse attention masks. Reference:
    python/paddle/nn/functional/flash_attention.py:1299.

    startend_row_indices: int32 [b, 1|h, sk, {1,2,4}] per-key-column row
    ranges (LTS / LTS,LTE / LTS,UTE / LTS,LTE,UTS,UTE — see reference
    docstring). TPU lowering: the ranges expand to an additive bias that
    the Pallas kernel STREAMS tile-by-tile (the score matrix still never
    materializes; a natively column-sparse Pallas variant is future work,
    so memory is O(s^2) for the bias where the CUDA kernel is O(s)).
    window_size composes as in the reference (sliding-window attention)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    if dropout:
        raise NotImplementedError(
            "flashmask_attention: attention dropout is not implemented in "
            "the TPU flash kernel; train with dropout=0.0")

    from paddle_tpu.ops.pallas.flash_attention import (NEG_INF,
                                                       flash_attention)

    if startend_row_indices is None and window_size is None:
        # plain (causal) attention — keep the maskless fast path
        return flash_attention(q, k, v, causal=causal, scale=scale)
    i = jnp.arange(sq)[None, None, :, None]     # query row
    j = jnp.arange(sk)[None, None, None, :]     # key column
    masked = jnp.zeros((1, 1, sq, sk), bool)
    if startend_row_indices is not None:
        idx = startend_row_indices.astype(jnp.int32)   # [b, kh, sk, n]
        n = idx.shape[-1]
        col = lambda c: idx[..., c][:, :, None, :]     # [b, kh, 1, sk]
        if causal:
            lts = col(0)
            lte = col(1) if n >= 2 else jnp.full_like(lts, sq)
            masked = (i >= lts) & (i < lte)
        elif n == 2:
            lts, ute = col(0), col(1)
            masked = ((i > j) & (i >= lts)) | ((i < j) & (i < ute))
        elif n == 4:
            lts, lte, uts, ute = col(0), col(1), col(2), col(3)
            masked = (((i > j) & (i >= lts) & (i < lte))
                      | ((i < j) & (i >= uts) & (i < ute)))
        else:
            raise ValueError(
                f"startend_row_indices last dim {n} invalid for "
                f"causal={causal}")
    if window_size is not None:
        w = ((window_size, window_size) if isinstance(window_size, int)
             else tuple(window_size))
        outside = (j < i - w[0]) if causal else ((j < i - w[0])
                                                | (j > i + w[1]))
        masked = masked | outside
    mask = jnp.where(masked, NEG_INF, 0.0).astype(jnp.float32)
    return flash_attention(q, k, v, causal=causal, scale=scale, mask=mask)


def sparse_attention(q, k, v, offset, columns, key_padding_mask=None,
                     attn_mask=None):
    """CSR-pattern sparse attention. Reference: the legacy sparse_attention
    op (paddle/phi/kernels/sparse/gpu/sparse_attention via
    nn.functional.sparse_attention): per-row allowed key columns given as
    CSR (offset [b, h, M+1], columns [b, h, nnz]); softmax runs over only
    those entries.

    TPU lowering: the CSR pattern expands to (a) an exact additive mask
    streamed tile-wise and (b) a tile-granular block mask — the Pallas
    kernel SKIPS the all-dead tiles' matmuls entirely, so block-structured
    patterns (local windows, block-diagonal, global tokens) get real
    compute sparsity, not just masked-dense semantics. Memory note: the
    expanded elementwise mask is O(b*h*M^2) HBM (arbitrary CSR patterns
    need it — the same bound as the reference's dense-mask route);
    compute is what the block mask sparsifies. key_padding_mask [b, M] (1 = keep) and additive
    attn_mask [b, h|1, M, M] compose with the pattern as in the
    reference.

    Layout [b, num_heads, M, d] (the reference op's convention)."""
    from paddle_tpu.ops.pallas.flash_attention import (NEG_INF,
                                                      flash_attention)

    b, h, M, d = q.shape
    offset = offset.astype(jnp.int32)
    columns = columns.astype(jnp.int32)
    nnz = columns.shape[-1]
    # row id of each CSR entry: highest r with offset[r] <= i (vectorized
    # searchsorted per (b, h) row table)
    flat_off = offset.reshape(b * h, M + 1)
    flat_col = columns.reshape(b * h, nnz)
    pos = jnp.arange(nnz)

    def rows_of(off_row):
        return jnp.searchsorted(off_row, pos, side="right") - 1

    row_ids = jax.vmap(rows_of)(flat_off)                 # [b*h, nnz]
    # entries past offset[-1] are padding; park them at row 0 masked off
    valid = pos[None, :] < flat_off[:, -1:]
    keep = jnp.zeros((b * h, M, M), bool)
    bh_idx = jnp.repeat(jnp.arange(b * h), nnz)
    keep = keep.at[bh_idx,
                   jnp.where(valid, row_ids, 0).reshape(-1),
                   jnp.where(valid, flat_col, 0).reshape(-1)].max(
        valid.reshape(-1))
    keep = keep.reshape(b, h, M, M)
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask)
        if kpm.dtype != jnp.bool_:
            kpm = kpm > 0
        keep = keep & kpm[:, None, None, :]            # [b, M] key-side
    mask = jnp.where(keep, 0.0, NEG_INF).astype(jnp.float32)
    if attn_mask is not None:
        am = jnp.asarray(attn_mask)
        if am.dtype == jnp.bool_:
            am = jnp.where(am, 0.0, NEG_INF)
        mask = mask + am.astype(jnp.float32)           # additive compose
    keep = keep & (mask > NEG_INF * 0.5)               # for the block mask

    block = 128 if M % 128 == 0 else M
    if M % block == 0:
        nb = M // block
        tiles = keep.reshape(b * h, nb, block, nb, block)
        block_mask = tiles.any(axis=(0, 2, 4)).astype(jnp.int32)
    else:
        block_mask = None

    qT = jnp.swapaxes(q, 1, 2)        # -> [b, M, h, d] kernel layout
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qT, kT, vT, causal=False, mask=mask,
                          block_mask=block_mask)
    return jnp.swapaxes(out, 1, 2)


def rotary_embedding(q, k, cos, sin, position_ids=None):
    """Reference: fused_rotary_position_embedding (incubate/nn/functional).
    q,k: [b, s, h, d]; cos/sin: [s, d] or broadcastable."""

    def rotate_half(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([-x2, x1], axis=-1)

    if position_ids is not None:
        cos = jnp.take(cos, position_ids, axis=0)
        sin = jnp.take(sin, position_ids, axis=0)
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    q_out = q * cos + rotate_half(q) * sin
    k_out = k * cos + rotate_half(k) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


# ============================================================ statistics+


def histogram(x, bins=100, min=0, max=0):  # noqa: A002
    """min == max == 0 means full data range (paddle semantics)."""
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    return _histogram_fixed(x, bins, lo, hi)


def _histogram_fixed(x, bins, lo, hi):
    edges = jnp.linspace(lo, hi, bins + 1)
    idx = jnp.clip(jnp.searchsorted(edges, x.ravel(), side="right") - 1,
                   0, bins - 1)
    inside = (x.ravel() >= lo) & (x.ravel() <= hi)
    return jnp.zeros(bins, jnp.int32).at[idx].add(inside.astype(jnp.int32))


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def kthvalue(x, k, axis=None, keepdim=False):
    if axis is None:
        axis = -1  # paddle semantics: default = last dim
    idxs = jnp.argsort(x, axis=axis)
    vals = jnp.take_along_axis(x, idxs, axis=axis)  # one sort, both outputs
    taken = jnp.take(vals, k - 1, axis=axis)
    itaken = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        taken = jnp.expand_dims(taken, axis)
        itaken = jnp.expand_dims(itaken, axis)
    return taken, itaken.astype(_canon(jnp.int64))


def mode(x, axis=-1, keepdim=False):
    """Returns (values, indices) like paddle.mode."""

    def mode_1d(v):
        vals, counts = jnp.unique_counts(v, size=v.shape[0], fill_value=v[0])
        winner = vals[jnp.argmax(counts)]
        # paddle returns the LAST index of the modal value
        pos = jnp.where(v == winner, jnp.arange(v.shape[0]), -1)
        return winner, jnp.max(pos)

    moved = jnp.moveaxis(x, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vs, idxs = jax.vmap(mode_1d)(flat)  # one pass computes both outputs
    out_v = vs.reshape(moved.shape[:-1])
    out_i = idxs.reshape(moved.shape[:-1])
    if keepdim:
        out_v = jnp.expand_dims(out_v, axis)
        out_i = jnp.expand_dims(out_i, axis)
    return out_v, out_i.astype(_canon(jnp.int64))


def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


def trapezoid(y, x=None, dx=None, axis=-1):
    return jnp.trapezoid(y, x=x, dx=1.0 if dx is None else dx, axis=axis)


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def gcd(x, y):
    return jnp.gcd(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def deg2rad(x):
    return jnp.deg2rad(x)


def rad2deg(x):
    return jnp.rad2deg(x)


def angle(x):
    return jnp.angle(x)


def conj(x):
    return jnp.conj(x)


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def renorm(x, p, axis, max_norm):
    dims = [d for d in range(x.ndim) if d != axis]
    norms = jnp.sum(jnp.abs(x) ** p, axis=dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor

# round-2 surface expansion — star import puts batch-2 impls in this
# namespace so the registry's getattr(impl_mod, name) finds them
from paddle_tpu.ops.impl_extra import *  # noqa: F401,F403,E402

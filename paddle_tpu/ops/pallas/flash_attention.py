"""Flash attention as a Pallas TPU kernel.

Reference: the reference wraps the CUDA flashattn library
(paddle/phi/kernels/gpu/flash_attn_kernel.cu over third_party/flashattn,
exposed via nn/functional/flash_attention.py:358). On TPU the kernel is
written in Pallas: blocks of Q stream against K/V tiles held in VMEM with an
online-softmax accumulator in fp32 — the attention matrix never exists in
HBM. MXU does the two matmuls per tile; the VPU does the softmax algebra.

Forward is the Pallas kernel; backward uses jax.custom_vjp with a
rematerialized reference backward (block-sparse flash backward is a follow-up
— forward is where serving/inference lives).

Layout: [batch, seq, heads, head_dim] (paddle flash-attn convention).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent meanings fall back to defaults
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float, seq_k: int, seq_q: int):
    """One (batch*head, q_block) program: stream K/V tiles, online softmax.

    q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq_k, d]; o_ref: [1, block_q, d]
    (leading unit dim = the batch*head grid axis).
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    # bottom-right-aligned causal mask (matches the XLA path's
    # tril(k=sk-sq)): query i attends keys <= i + (seq_k - seq_q)
    causal_offset = seq_k - seq_q
    q_pos = causal_offset + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_tile = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_tile = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_tile, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        new_acc = acc * corr + jax.lax.dot_general(
            p, v_tile, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, new_l, new_acc

    num_kb = seq_k // block_k
    if causal:
        # only tiles that intersect the causal region for this q block
        num_kb_live = jnp.minimum(
            causal_offset + (qi + 1) * block_q + block_k - 1, seq_k) // block_k
        m, l, acc = jax.lax.fori_loop(0, num_kb_live, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))

    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    """q/k/v: [b, s, h, d] -> out [b, s, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)

    grid = (b * h, sq // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        seq_k=sk, seq_q=sq)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


def _reference(q, k, v, causal, scale):
    qT = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kT = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vT = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qT, kT) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vT)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _block_shapes_ok(q, k, block_q, block_k, v=None) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    return (sq % block_q == 0 and sk % block_k == 0 and d % 128 == 0
            and q.shape[:1] + q.shape[2:] == k.shape[:1] + k.shape[2:]
            and (v is None or tuple(v.shape) == tuple(k.shape)))


def flash_attention(q, k, v, causal: bool = True, scale=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Pallas flash attention with automatic fallback to the XLA reference
    when shapes don't tile (same dispatch pattern as the reference's
    sdp_kernel selection, nn/functional/flash_attention.py)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if not _block_shapes_ok(q, k, block_q, block_k):
        return _reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)

"""Flash attention as a Pallas TPU kernel.

Reference: the reference wraps the CUDA flashattn library
(paddle/phi/kernels/gpu/flash_attn_kernel.cu over third_party/flashattn,
exposed via nn/functional/flash_attention.py:358). On TPU the kernel is
written in Pallas: grid (batch*head, q_blocks, k_blocks) with the K axis
innermost, VMEM scratch accumulators (running max / denom / output) carried
across K tiles, fp32 online softmax — only one (block_q, d) Q tile and one
(block_k, d) K/V tile are VMEM-resident per step, so memory is independent
of sequence length and the attention matrix never exists in HBM. MXU does
the two matmuls per tile; the VPU does the softmax algebra.

Forward and backward are Pallas kernels (FlashAttention-2 style backward:
a dQ kernel accumulating over K tiles and a dK/dV kernel accumulating over
Q tiles, both recomputing P from the saved per-row log-sum-exp).

Layout: [batch, seq, heads, head_dim] (paddle flash-attn convention).
Causal masking is bottom-right aligned (tril k=sk-sq), matching the XLA
reference path for cross-length (KV-decode) shapes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces (absent on pure-CPU builds)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Per-row stats (lse, delta) ride a small trailing lane dim so their block
# shapes satisfy the Mosaic tiling rule (last dim == array dim); 8 keeps the
# HBM cost at 8 floats/row instead of a full 128-lane broadcast.
LSE_LANES = 8


def _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k, causal, scale,
                 seq_k, seq_q):
    """Shared per-tile scaled+masked scores (ONE definition of the causal
    mask for fwd and both bwd kernels)."""
    q = q_ref[0].astype(jnp.float32)
    k_tile = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k_tile, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_start = (seq_k - seq_q) + qi * block_q
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return q, k_tile, s


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
              block_q, block_k, causal, scale, seq_k, seq_q):
    """Shared backward tile math: recompute P from lse, form dS."""
    q, k_tile, s = _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k,
                                causal, scale, seq_k, seq_q)
    v_tile = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    # lse/delta are stored value-broadcast over a trailing LSE_LANES dim
    # (Mosaic block rule: last block dim must divide 128 or equal the array
    # dim — a bare (1, block_q) spec is not lowerable); read one lane back.
    lse = lse_ref[0][:, :1].astype(jnp.float32)
    delta = delta_ref[0][:, :1].astype(jnp.float32)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v_tile, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return q, k_tile, do, p, ds


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                      acc_ref, *, block_q: int, block_k: int, causal: bool,
                      scale: float, seq_k: int, seq_q: int):
    """One grid step: fold one K/V tile into this Q block's accumulators."""
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    # bottom-right-aligned causal offset: query i sees keys <= i + (sk - sq)
    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    live = (ki * block_k <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _tile():
        _, _, s = _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k,
                               causal, scale, seq_k, seq_q)
        v_tile = v_ref[0].astype(jnp.float32)
        m = m_ref[:]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        m_ref[:] = new_m
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v_tile, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp per row, saved for the backward kernels
            # (broadcast across the LSE_LANES lane dim)
            lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))
            lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], LSE_LANES))


def _fwd_kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                      **kw):
    """Inference variant: no lse output (saves a discarded HBM write)."""
    _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref,
                      acc_ref, **kw)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool, with_lse: bool = False):
    """q/k/v: [b, s, h, d] -> out [b, s, h, d] (+ lse [b*h, sq, LSE_LANES]
    fp32, value-broadcast across the trailing lane dim)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)

    grid = (b * h, sq // block_q, sk // block_k)
    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale, seq_k=sk, seq_q=sq)

    scratch = [
        _scratch((block_q, 1)),
        _scratch((block_q, 1)),
        _scratch((block_q, d)),
    ]
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    if with_lse:
        out, lse = pl.pallas_call(
            functools.partial(_flash_fwd_kernel, **common),
            out_shape=(jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                       jax.ShapeDtypeStruct((b * h, sq, LSE_LANES),
                                            jnp.float32)),
            grid=grid, in_specs=in_specs,
            out_specs=(o_spec,
                       pl.BlockSpec((1, block_q, LSE_LANES),
                                    lambda bh, qi, ki: (bh, qi, 0))),
            scratch_shapes=scratch, interpret=interpret,
        )(qf, kf, vf)
        return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2), lse
    out = pl.pallas_call(
        functools.partial(_fwd_kernel_nolse, **common),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid, in_specs=in_specs, out_specs=o_spec,
        scratch_shapes=scratch, interpret=interpret,
    )(qf, kf, vf)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.pallas_call  # unreachable on CPU (interpret handles VMEM spec)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *, block_q, block_k, causal, scale,
                         seq_k, seq_q):
    """dQ_i = scale * sum_j dS_ij K_j, dS = P * (dO V^T - delta).
    Grid (bh, qi, ki); accumulate over ki in VMEM scratch."""
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    live = (ki * block_k <= q_start + block_q - 1) if causal else True

    @pl.when(live)
    def _tile():
        _, k_t, _, _, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                     delta_ref, qi, ki, block_q, block_k,
                                     causal, scale, seq_k, seq_q)
        acc_ref[:] += scale * jax.lax.dot_general(
            ds, k_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                          causal, scale, seq_k, seq_q):
    """dV_j = P^T dO; dK_j = scale * dS^T Q. Grid (bh, ki, qi); accumulate
    over qi in VMEM scratch."""
    d = q_ref.shape[-1]
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_qb = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc[:] = jnp.zeros((block_k, d), jnp.float32)

    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    # this q block contributes iff its LAST query can see this k tile
    live = (q_start + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _tile():
        q, _, do, p, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, qi, ki, block_q, block_k,
                                    causal, scale, seq_k, seq_q)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, do, lse, causal, scale, block_q, block_k,
                    interpret):
    """Returns (dq, dk, dv) in the [b, s, h, d] layout."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    flat = lambda t, s: jnp.swapaxes(t, 1, 2).reshape(b * h, s, d)
    qf, kf, vf = flat(q, sq), flat(k, sk), flat(v, sk)
    of, dof = flat(o, sq), flat(do, sq)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA fuses it;
    # broadcast over LSE_LANES to match the kernels' per-row-stat layout
    delta = jnp.sum(of.astype(jnp.float32) * dof.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq, LSE_LANES))

    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale, seq_k=sk, seq_q=sq)

    # ---- dQ: grid (bh, qi, ki) -------------------------------------------
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # ---- dK/dV: grid (bh, ki, qi) ----------------------------------------
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ),
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    unflat = lambda t, s: jnp.swapaxes(t.reshape(b, h, s, d), 1, 2)
    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


def _reference(q, k, v, causal, scale):
    qT = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kT = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vT = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qT, kT) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vT)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, g, lse, causal, scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _block_shapes_ok(q, k, block_q, block_k, v=None) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # d % 8 == 0: Mosaic pads sub-128 lane dims, so head_dim 64 (the GPT
    # 512/8 flagship and most small/medium models) runs the flash kernel
    # instead of silently falling back to the O(seq^2) XLA path.
    return (sq % block_q == 0 and sk % block_k == 0 and d % 8 == 0
            and q.shape[:1] + q.shape[2:] == k.shape[:1] + k.shape[2:]
            and (v is None or tuple(v.shape) == tuple(k.shape)))


DEFAULT_CHECK_SHAPES = ((1, 256, 4, 64), (2, 512, 8, 64), (1, 256, 4, 128))


def validate_against_reference(shapes=DEFAULT_CHECK_SHAPES, interpret=None,
                               tol_out=None, tol_grad=None, seed=0):
    """Run the Pallas kernels (fwd + bwd) against the XLA reference path and
    return {"max_abs_err", "shapes": [[b,s,h,d,err_o,err_g],...], "pass"}.

    Single source of truth for the kernel-vs-reference criterion — used by
    both the bench ladder's on-hardware check and the TPU pytest tier, so
    the two can't drift apart."""
    import numpy as np

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Interpret mode computes dots in true fp32 — hold it to tight bounds.
    # On the MXU, fp32 dots run as bf16 multi-pass (default precision), so
    # both the kernel and the XLA reference carry ~2^-8 relative rounding;
    # the comparison bound must absorb it.
    if tol_out is None:
        tol_out = 2e-3 if interpret else 2e-2
    if tol_grad is None:
        tol_grad = 5e-2 if interpret else 1e-1
    rng = np.random.default_rng(seed)
    worst = 0.0
    checked = []
    ok = True
    for (b, s, h, d) in shapes:
        q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)),
                               jnp.float32) for _ in range(3))
        scale = 1.0 / math.sqrt(d)
        o_f = _flash(q, k, v, True, scale, 128, 128, interpret)
        o_r = _reference(q, k, v, True, scale)
        g_f = jax.grad(lambda *a: jnp.sum(
            _flash(*a, True, scale, 128, 128, interpret) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(lambda *a: jnp.sum(
            _reference(*a, True, scale) ** 2), argnums=(0, 1, 2))(q, k, v)
        err_o = float(jnp.max(jnp.abs(o_f - o_r)))
        err_g = max(float(jnp.max(jnp.abs(x - y)))
                    for x, y in zip(g_f, g_r))
        worst = max(worst, err_o, err_g)
        ok = ok and err_o < tol_out and err_g < tol_grad
        checked.append([b, s, h, d, err_o, err_g])
    return {"max_abs_err": worst, "shapes": checked, "pass": ok,
            "interpret": interpret}


_FALLBACK_WARNED: set = set()


def _log_fallback(q, k, block_q, block_k):
    """The silent-fallback condition is a dead-kernel bug magnet — warn once
    per shape so it is visible which configs miss the flash path."""
    key = (tuple(q.shape), tuple(k.shape), block_q, block_k)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        import warnings

        warnings.warn(
            f"flash_attention: shapes q={tuple(q.shape)} k={tuple(k.shape)} "
            f"don't tile (block_q={block_q}, block_k={block_k}); using the "
            "O(seq^2) XLA reference path", stacklevel=3)


def flash_attention(q, k, v, causal: bool = True, scale=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Pallas flash attention with automatic fallback to the XLA reference
    when shapes don't tile (same dispatch pattern as the reference's
    sdp_kernel selection, nn/functional/flash_attention.py)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if causal and q.shape[1] > k.shape[1]:
        # bottom-right alignment gives early queries ZERO visible keys; the
        # backward lse recomputation is ill-defined for such rows (fp32
        # absorbs log(l) into -1e30) — use the XLA path for this shape
        _log_fallback(q, k, block_q, block_k)
        return _reference(q, k, v, causal, scale)
    if not _block_shapes_ok(q, k, block_q, block_k, v=v):
        _log_fallback(q, k, block_q, block_k)
        return _reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)

"""Flash attention as a Pallas TPU kernel — dense, masked, and varlen.

Reference: the reference wraps the CUDA flashattn library
(paddle/phi/kernels/gpu/flash_attn_kernel.cu over third_party/flashattn,
exposed via nn/functional/flash_attention.py:358, flash_attn_unpadded at
:756 and flashmask_attention at :1299). On TPU the kernel is written in
Pallas: grid (batch*head, q_blocks, k_blocks) with the K axis innermost,
VMEM scratch accumulators (running max / denom / output) carried across K
tiles, fp32 online softmax — only one (block_q, d) Q tile and one
(block_k, d) K/V tile are VMEM-resident per step, so memory is independent
of sequence length and the attention matrix never exists in HBM. MXU does
the two matmuls per tile; the VPU does the softmax algebra.

Masking (four independent mechanisms, composable with `causal`):
  * additive mask — an fp32 [b, 1|h, sq, sk] bias streamed tile-by-tile
    into VMEM and added to the scores (the reference's attn_mask semantic;
    the bias itself is O(s^2) HBM but the score matrix still never
    materializes and the read is fused into the attention loop);
  * kv bias — an fp32 [b, sk] per-KEY additive bias streamed as
    (1, block_k) tiles: the O(s) form of the ubiquitous key-padding mask
    ([b, 1, 1, sk] attn_mask shapes lower here, NOT to a dense O(s^2)
    broadcast), exact additive semantics at every query row;
  * segment ids — int32 [b, sq] / [b, sk] per-token ids; attention is
    allowed only where q_seg == k_seg. This is the varlen/packed form:
    flash_attn_unpadded's cu_seqlens lower onto it with O(s) memory, the
    same design as jax.experimental.pallas.ops.tpu flash attention;
  * bool masks are canonicalized to additive NEG_INF outside the kernel.

Fully-masked rows are well-defined: the online-softmax guard zeroes
probabilities where the score is hard-masked, so such rows produce 0
output and 0 gradient instead of NaN.

Forward and backward are Pallas kernels (FlashAttention-2 style backward:
a dQ kernel accumulating over K tiles and a dK/dV kernel accumulating over
Q tiles, both recomputing P from the saved per-row log-sum-exp).

Layout: [batch, seq, heads, head_dim] (paddle flash-attn convention).
Causal masking is bottom-right aligned (tril k=sk-sq), matching the XLA
reference path for cross-length (KV-decode) shapes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces (absent on pure-CPU builds)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# Hard-mask detection threshold for the fully-masked-row guard: scores at
# or below this are treated as "structurally masked" and contribute exactly
# zero probability in both fwd and bwd (real scores never get near -5e29).
MASKED_BELOW = NEG_INF * 0.5
# Per-row stats (lse, delta) ride a small trailing lane dim so their block
# shapes satisfy the Mosaic tiling rule (last dim == array dim); 8 keeps the
# HBM cost at 8 floats/row instead of a full 128-lane broadcast.
LSE_LANES = 8


def _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k, causal, scale,
                 seq_k, seq_q, mask_ref=None, kbias_ref=None, qseg_ref=None,
                 kseg_ref=None):
    """Shared per-tile scaled+masked scores (ONE definition of the causal /
    additive / kv-bias / segment masks for fwd and both bwd kernels)."""
    q = q_ref[0].astype(jnp.float32)
    k_tile = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k_tile, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if mask_ref is not None:
        s = s + mask_ref[0].astype(jnp.float32)
    if kbias_ref is not None:
        s = s + kbias_ref[0].astype(jnp.float32)[None, :]
    if qseg_ref is not None:
        qs = qseg_ref[0]
        ks = kseg_ref[0]
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    if causal:
        q_start = (seq_k - seq_q) + qi * block_q
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    return q, k_tile, s


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
              block_q, block_k, causal, scale, seq_k, seq_q,
              mask_ref=None, kbias_ref=None, qseg_ref=None, kseg_ref=None):
    """Shared backward tile math: recompute P from lse, form dS."""
    q, k_tile, s = _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k,
                                causal, scale, seq_k, seq_q,
                                mask_ref, kbias_ref, qseg_ref, kseg_ref)
    v_tile = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    # lse/delta are stored value-broadcast over a trailing LSE_LANES dim
    # (Mosaic block rule: last block dim must divide 128 or equal the array
    # dim — a bare (1, block_q) spec is not lowerable); read one lane back.
    lse = lse_ref[0][:, :1].astype(jnp.float32)
    delta = delta_ref[0][:, :1].astype(jnp.float32)
    # hard-masked entries get exactly 0 even on fully-masked rows where the
    # saved lse is itself ~NEG_INF (exp(s - lse) would be exp(0) = 1 there)
    p = jnp.where(s <= MASKED_BELOW, 0.0, jnp.exp(s - lse))
    dp = jax.lax.dot_general(do, v_tile, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    return q, k_tile, do, p, ds


def _split_refs(refs, n_lead, has_mask, has_kbias, has_seg,
                has_blockmask=False):
    """Peel (mask_ref, kbias_ref, qseg_ref, kseg_ref, bm_ref, rest) off a
    flat pallas ref list after the first `n_lead` fixed inputs."""
    i = n_lead
    mask_ref = kbias_ref = qseg_ref = kseg_ref = bm_ref = None
    if has_mask:
        mask_ref = refs[i]
        i += 1
    if has_kbias:
        kbias_ref = refs[i]
        i += 1
    if has_seg:
        qseg_ref, kseg_ref = refs[i], refs[i + 1]
        i += 2
    if has_blockmask:
        bm_ref = refs[i]
        i += 1
    return mask_ref, kbias_ref, qseg_ref, kseg_ref, bm_ref, refs[i:]


def _flash_fwd_kernel(*refs, block_q: int, block_k: int, causal: bool,
                      scale: float, seq_k: int, seq_q: int, has_mask: bool,
                      has_kbias: bool, has_seg: bool, has_blockmask: bool,
                      with_lse: bool):
    """One grid step: fold one K/V tile into this Q block's accumulators."""
    q_ref, k_ref, v_ref = refs[:3]
    mask_ref, kbias_ref, qseg_ref, kseg_ref, bm_ref, rest = _split_refs(
        refs, 3, has_mask, has_kbias, has_seg, has_blockmask)
    if with_lse:
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        (o_ref, m_ref, l_ref, acc_ref), lse_ref = rest, None
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    # bottom-right-aligned causal offset: query i sees keys <= i + (sk - sq)
    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    live = (ki * block_k <= q_start + block_q - 1) if causal else True
    if bm_ref is not None:
        # block-sparse: whole (qi, ki) tiles named dead by the block mask
        # skip their matmuls entirely (pl.when guards real FLOPs)
        live = live & (bm_ref[qi, ki] > 0)

    @pl.when(live)
    def _tile():
        _, _, s = _tile_scores(q_ref, k_ref, qi, ki, block_q, block_k,
                               causal, scale, seq_k, seq_q,
                               mask_ref, kbias_ref, qseg_ref, kseg_ref)
        v_tile = v_ref[0].astype(jnp.float32)
        m = m_ref[:]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        # guard: on a row where every key so far is hard-masked, new_m is
        # still NEG_INF and exp(s - new_m) would be exp(0) = 1 — force 0 so
        # the row's l stays 0 and its output is exactly zero
        p = jnp.where(s <= MASKED_BELOW, 0.0, jnp.exp(s - new_m))
        corr = jnp.exp(m - new_m)
        m_ref[:] = new_m
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v_tile, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp per row, saved for the backward kernels
            # (broadcast across the LSE_LANES lane dim)
            lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))
            lse_ref[0] = jnp.broadcast_to(lse, (lse.shape[0], LSE_LANES))


def _extra_inputs_specs(mask, kbias, qseg, kseg, h, block_q, block_k,
                        order, block_mask=None):
    """Streamed mask/kv-bias/segment/block-mask inputs + BlockSpecs.

    order 'qk': grid (bh, qi, ki) — fwd and the dQ kernel.
    order 'kq': grid (bh, ki, qi) — the dK/dV kernel.
    mask: [b, 1|h, sq, sk] additive fp32; kbias: [b, sk] additive fp32;
    segs: int32 [b, sq] / [b, sk]; block_mask: int32 [nq, nk] tile
    liveness (0 tiles are skipped — their FLOPs never run)."""
    inputs, specs = [], []
    if mask is not None:
        b, mh, sq, sk = mask.shape
        mf = mask.reshape(b * mh, sq, sk)
        if order == "qk":
            idx = ((lambda bh, qi, ki: (bh, qi, ki)) if mh != 1 else
                   (lambda bh, qi, ki: (bh // h, qi, ki)))
        else:
            idx = ((lambda bh, ki, qi: (bh, qi, ki)) if mh != 1 else
                   (lambda bh, ki, qi: (bh // h, qi, ki)))
        inputs.append(mf)
        specs.append(pl.BlockSpec((1, block_q, block_k), idx))
    if kbias is not None:
        if order == "qk":
            kbidx = lambda bh, qi, ki: (bh // h, ki)  # noqa: E731
        else:
            kbidx = lambda bh, ki, qi: (bh // h, ki)  # noqa: E731
        inputs.append(kbias.astype(jnp.float32))
        specs.append(pl.BlockSpec((1, block_k), kbidx))
    if qseg is not None:
        if order == "qk":
            qidx = lambda bh, qi, ki: (bh // h, qi)   # noqa: E731
            kidx = lambda bh, qi, ki: (bh // h, ki)   # noqa: E731
        else:
            qidx = lambda bh, ki, qi: (bh // h, qi)   # noqa: E731
            kidx = lambda bh, ki, qi: (bh // h, ki)   # noqa: E731
        inputs += [qseg.astype(jnp.int32), kseg.astype(jnp.int32)]
        specs += [pl.BlockSpec((1, block_q), qidx),
                  pl.BlockSpec((1, block_k), kidx)]
    if block_mask is not None:
        # the whole [n_qblocks, n_kblocks] table rides in VMEM (tiny);
        # every grid step indexes it by (qi, ki)
        nq, nk = block_mask.shape
        inputs.append(block_mask.astype(jnp.int32))
        specs.append(pl.BlockSpec((nq, nk), lambda *_: (0, 0)))
    return inputs, specs


def _flash_forward(q, k, v, mask, kbias, qseg, kseg, block_mask,
                   causal: bool, scale: float, block_q: int, block_k: int,
                   interpret: bool, with_lse: bool = False):
    """q/k/v: [b, s, h, d] -> out [b, s, h, d] (+ lse [b*h, sq, LSE_LANES]
    fp32, value-broadcast across the trailing lane dim)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]

    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)

    grid = (b * h, sq // block_q, sk // block_k)
    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale, seq_k=sk, seq_q=sq,
                  has_mask=mask is not None, has_kbias=kbias is not None,
                  has_seg=qseg is not None,
                  has_blockmask=block_mask is not None, with_lse=with_lse)

    scratch = [
        _scratch((block_q, 1)),
        _scratch((block_q, 1)),
        _scratch((block_q, d)),
    ]
    extra_in, extra_specs = _extra_inputs_specs(
        mask, kbias, qseg, kseg, h, block_q, block_k, "qk",
        block_mask=block_mask)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ] + extra_specs
    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    if with_lse:
        out, lse = pl.pallas_call(
            functools.partial(_flash_fwd_kernel, **common),
            out_shape=(jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                       jax.ShapeDtypeStruct((b * h, sq, LSE_LANES),
                                            jnp.float32)),
            grid=grid, in_specs=in_specs,
            out_specs=(o_spec,
                       pl.BlockSpec((1, block_q, LSE_LANES),
                                    lambda bh, qi, ki: (bh, qi, 0))),
            scratch_shapes=scratch, interpret=interpret,
        )(qf, kf, vf, *extra_in)
        return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2), lse
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid, in_specs=in_specs, out_specs=o_spec,
        scratch_shapes=scratch, interpret=interpret,
    )(qf, kf, vf, *extra_in)
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.pallas_call  # unreachable on CPU (interpret handles VMEM spec)


def _flash_bwd_dq_kernel(*refs, block_q, block_k, causal, scale, seq_k,
                         seq_q, has_mask, has_kbias, has_seg,
                         has_blockmask):
    """dQ_i = scale * sum_j dS_ij K_j, dS = P * (dO V^T - delta).
    Grid (bh, qi, ki); accumulate over ki in VMEM scratch."""
    q_ref, k_ref, v_ref, do_ref = refs[:4]
    mask_ref, kbias_ref, qseg_ref, kseg_ref, bm_ref, rest = _split_refs(
        refs, 4, has_mask, has_kbias, has_seg, has_blockmask)
    lse_ref, delta_ref, dq_ref, acc_ref = rest
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    live = (ki * block_k <= q_start + block_q - 1) if causal else True
    if bm_ref is not None:
        live = live & (bm_ref[qi, ki] > 0)

    @pl.when(live)
    def _tile():
        _, k_t, _, _, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                     delta_ref, qi, ki, block_q, block_k,
                                     causal, scale, seq_k, seq_q, mask_ref,
                                     kbias_ref, qseg_ref, kseg_ref)
        acc_ref[:] += scale * jax.lax.dot_general(
            ds, k_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, block_q, block_k, causal, scale, seq_k,
                          seq_q, has_mask, has_kbias, has_seg,
                          has_blockmask):
    """dV_j = P^T dO; dK_j = scale * dS^T Q. Grid (bh, ki, qi); accumulate
    over qi in VMEM scratch."""
    q_ref, k_ref, v_ref, do_ref = refs[:4]
    mask_ref, kbias_ref, qseg_ref, kseg_ref, bm_ref, rest = _split_refs(
        refs, 4, has_mask, has_kbias, has_seg, has_blockmask)
    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    d = q_ref.shape[-1]
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_qb = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc[:] = jnp.zeros((block_k, d), jnp.float32)

    causal_offset = seq_k - seq_q
    q_start = causal_offset + qi * block_q
    # this q block contributes iff its LAST query can see this k tile
    live = (q_start + block_q - 1 >= ki * block_k) if causal else True
    if bm_ref is not None:
        live = live & (bm_ref[qi, ki] > 0)

    @pl.when(live)
    def _tile():
        q, _, do, p, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, qi, ki, block_q, block_k,
                                    causal, scale, seq_k, seq_q, mask_ref,
                                    kbias_ref, qseg_ref, kseg_ref)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, do, lse, mask, kbias, qseg, kseg,
                    block_mask, causal, scale, block_q, block_k,
                    interpret):
    """Returns (dq, dk, dv) in the [b, s, h, d] layout."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    flat = lambda t, s: jnp.swapaxes(t, 1, 2).reshape(b * h, s, d)
    qf, kf, vf = flat(q, sq), flat(k, sk), flat(v, sk)
    of, dof = flat(o, sq), flat(do, sq)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, XLA fuses it;
    # broadcast over LSE_LANES to match the kernels' per-row-stat layout
    delta = jnp.sum(of.astype(jnp.float32) * dof.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq, LSE_LANES))

    common = dict(block_q=block_q, block_k=block_k, causal=causal,
                  scale=scale, seq_k=sk, seq_q=sq,
                  has_mask=mask is not None, has_kbias=kbias is not None,
                  has_seg=qseg is not None,
                  has_blockmask=block_mask is not None)

    # ---- dQ: grid (bh, qi, ki) -------------------------------------------
    extra_in, extra_specs = _extra_inputs_specs(
        mask, kbias, qseg, kseg, h, block_q, block_k, "qk",
        block_mask=block_mask)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        ] + extra_specs + [
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi, ki: (bh, qi, 0)),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(qf, kf, vf, dof, *extra_in, lse, delta)

    # ---- dK/dV: grid (bh, ki, qi) ----------------------------------------
    extra_in, extra_specs = _extra_inputs_specs(
        mask, kbias, qseg, kseg, h, block_q, block_k, "kq",
        block_mask=block_mask)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        out_shape=(jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, sk, d), v.dtype)),
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
        ] + extra_specs + [
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, LSE_LANES),
                         lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ),
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(qf, kf, vf, dof, *extra_in, lse, delta)

    unflat = lambda t, s: jnp.swapaxes(t.reshape(b, h, s, d), 1, 2)
    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


def _reference(q, k, v, causal, scale, mask=None, kbias=None, qseg=None,
               kseg=None):
    qT = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kT = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vT = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qT, kT) * scale
    if mask is not None:
        s = s + mask.astype(jnp.float32)        # [b, 1|h, sq, sk] additive
    if kbias is not None:
        s = s + kbias.astype(jnp.float32)[:, None, None, :]  # [b, sk]
    if qseg is not None:
        seg_ok = qseg[:, None, :, None] == kseg[:, None, None, :]
        s = jnp.where(seg_ok, s, NEG_INF)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(cm[None, None], s, NEG_INF)
    # match the kernel's fully-masked-row semantics: such rows output 0
    row_live = jnp.any(s > MASKED_BELOW, axis=-1, keepdims=True)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(row_live, p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vT)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _zero_cot(x):
    """Zero cotangent matching a primal that the kernel treats as constant
    (mask / segment ids); None passes through, ints get float0."""
    if x is None:
        return None
    if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_:
        return np.zeros(x.shape, jax.dtypes.float0)
    return jnp.zeros_like(x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12))
def _flash(q, k, v, mask, kbias, qseg, kseg, block_mask, causal, scale,
           block_q, block_k, interpret):
    return _flash_forward(q, k, v, mask, kbias, qseg, kseg, block_mask,
                          causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, mask, kbias, qseg, kseg, block_mask, causal,
               scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, mask, kbias, qseg, kseg, block_mask,
                              causal, scale, block_q, block_k, interpret,
                              with_lse=True)
    return out, (q, k, v, mask, kbias, qseg, kseg, block_mask, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, mask, kbias, qseg, kseg, block_mask, o, lse = res
    dq, dk, dv = _flash_backward(q, k, v, o, g, lse, mask, kbias, qseg,
                                 kseg, block_mask, causal, scale, block_q,
                                 block_k, interpret)
    return (dq, dk, dv, _zero_cot(mask), _zero_cot(kbias),
            _zero_cot(qseg), _zero_cot(kseg), _zero_cot(block_mask))


_flash.defvjp(_flash_fwd, _flash_bwd)


def _block_shapes_ok(q, k, block_q, block_k, v=None) -> bool:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # d % 8 == 0: Mosaic pads sub-128 lane dims, so head_dim 64 (the GPT
    # 512/8 flagship and most small/medium models) runs the flash kernel
    # instead of silently falling back to the O(seq^2) XLA path.
    return (sq % block_q == 0 and sk % block_k == 0 and d % 8 == 0
            and q.shape[:1] + q.shape[2:] == k.shape[:1] + k.shape[2:]
            and (v is None or tuple(v.shape) == tuple(k.shape)))


def _canon_mask(mask, b, h, sq, sk):
    """Canonicalize a paddle-style attn_mask. Accepts bool (True = attend,
    reference convention) or additive float, with broadcastable shapes.

    Returns (dense, kbias): key-padding forms [*, *, 1, sk] lower to a
    kbias [b, sk] (O(s) HBM, streamed as (1, block_k) tiles) with dense
    None; anything with a per-query axis becomes dense additive fp32
    [b, 1|h, sq, sk] with kbias None."""
    mask = jnp.asarray(mask)
    if mask.dtype == jnp.bool_:
        mask = jnp.where(mask, 0.0, NEG_INF).astype(jnp.float32)
    if mask.ndim == 2:          # [sq|1, sk]
        mask = mask[None, None]
    elif mask.ndim == 3:        # [b, sq|1, sk]
        mask = mask[:, None]
    if mask.ndim != 4:
        raise ValueError(f"attn mask rank {mask.ndim} not supported")
    if mask.shape[1] == 1 and mask.shape[2] == 1:
        # key-padding form: identical for every query row and head — do
        # NOT broadcast to O(s^2); stream as a per-key bias instead
        kbias = jnp.broadcast_to(mask[:, 0, 0, :].astype(jnp.float32),
                                 (b, sk))
        return None, kbias
    mh = 1 if mask.shape[1] == 1 else h
    return jnp.broadcast_to(mask.astype(jnp.float32),
                            (b, mh, sq, sk)), None


def _canon_segments(segment_ids, b, sq, sk):
    """segment_ids: int [b, s] (self-attention) or a (q_seg, kv_seg) pair;
    returns int32 ([b, sq], [b, sk])."""
    if isinstance(segment_ids, (tuple, list)):
        qseg, kseg = segment_ids
    else:
        qseg = kseg = segment_ids
    qseg = jnp.asarray(qseg, jnp.int32)
    kseg = jnp.asarray(kseg, jnp.int32)
    if qseg.shape != (b, sq) or kseg.shape != (b, sk):
        raise ValueError(
            f"segment_ids shapes {qseg.shape}/{kseg.shape} don't match "
            f"q/kv sequences ({b},{sq})/({b},{sk})")
    return qseg, kseg


DEFAULT_CHECK_SHAPES = ((1, 256, 4, 64), (2, 512, 8, 64), (1, 256, 4, 128))


def validate_against_reference(shapes=DEFAULT_CHECK_SHAPES, interpret=None,
                               tol_out=None, tol_grad=None, seed=0):
    """Run the Pallas kernels (fwd + bwd) against the XLA reference path and
    return {"max_abs_err", "shapes": [[b,s,h,d,mode,err_o,err_g],...],
    "pass"} — each shapes row carries 7 elements, with the attention mode
    string at index 4 (one of "dense", "densemask", "padbias", "segments",
    matching the case list built below).

    Covers the dense-causal, additive-padding-mask, and segment-id (varlen)
    paths. Single source of truth for the kernel-vs-reference criterion —
    used by both the bench ladder's on-hardware check and the TPU pytest
    tier, so the two can't drift apart."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Interpret mode computes dots in true fp32 — hold it to tight bounds.
    # On the MXU, fp32 dots run as bf16 multi-pass (default precision), so
    # both the kernel and the XLA reference carry ~2^-8 relative rounding;
    # the comparison bound must absorb it.
    if tol_out is None:
        tol_out = 2e-3 if interpret else 2e-2
    if tol_grad is None:
        tol_grad = 5e-2 if interpret else 1e-1
    rng = np.random.default_rng(seed)
    worst = 0.0
    checked = []
    ok = True
    # (shape, mode): dense causal for every shape, plus a dense-mask, a
    # kv-bias (padding) and a packed-segment case on the first shape
    cases = [(sh, "dense") for sh in shapes]
    cases += [(shapes[0], "densemask"), (shapes[0], "padbias"),
              (shapes[0], "segments")]
    for (b, s, h, d), mode in cases:
        q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)),
                               jnp.float32) for _ in range(3))
        scale = 1.0 / math.sqrt(d)
        mask = kbias = segs = None
        causal = mode not in ("densemask", "padbias")
        valid = jnp.arange(s) < (3 * s) // 4   # last quarter = padding
        if mode == "densemask":
            mask = jnp.broadcast_to(
                jnp.where(valid, 0.0, NEG_INF)[None, None, None, :],
                (b, 1, s, s)).astype(jnp.float32)
        elif mode == "padbias":
            # the O(s) key-padding form (ERNIE-style [b,1,1,sk] lowering)
            kbias = jnp.broadcast_to(
                jnp.where(valid, 0.0, NEG_INF)[None, :], (b, s)
            ).astype(jnp.float32)
        elif mode == "segments":
            segs = jnp.broadcast_to((jnp.arange(s) * 4) // s, (b, s)
                                    ).astype(jnp.int32)

        def f_f(q, k, v, mask=mask, kbias=kbias, segs=segs, causal=causal,
                scale=scale):
            qs, ks = (segs, segs) if segs is not None else (None, None)
            return _flash(q, k, v, mask, kbias, qs, ks, None, causal,
                          scale, 128, 128, interpret)

        def f_r(q, k, v, mask=mask, kbias=kbias, segs=segs, causal=causal,
                scale=scale):
            return _reference(q, k, v, causal, scale, mask=mask,
                              kbias=kbias, qseg=segs, kseg=segs)

        o_f = f_f(q, k, v)
        o_r = f_r(q, k, v)
        g_f = jax.grad(lambda *a: jnp.sum(f_f(*a) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(lambda *a: jnp.sum(f_r(*a) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
        err_o = float(jnp.max(jnp.abs(o_f - o_r)))
        err_g = max(float(jnp.max(jnp.abs(x - y)))
                    for x, y in zip(g_f, g_r))
        worst = max(worst, err_o, err_g)
        ok = ok and err_o < tol_out and err_g < tol_grad
        checked.append([b, s, h, d, mode, err_o, err_g])
    return {"max_abs_err": worst, "shapes": checked, "pass": ok,
            "interpret": interpret}


_FALLBACK_WARNED: set = set()


def _log_fallback(q, k, block_q, block_k):
    """The silent-fallback condition is a dead-kernel bug magnet — warn once
    per shape so it is visible which configs miss the flash path."""
    key = (tuple(q.shape), tuple(k.shape), block_q, block_k)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        import warnings

        warnings.warn(
            f"flash_attention: shapes q={tuple(q.shape)} k={tuple(k.shape)} "
            f"don't tile (block_q={block_q}, block_k={block_k}); using the "
            "O(seq^2) XLA reference path", stacklevel=3)


def flash_attention(q, k, v, causal: bool = True, scale=None,
                    mask=None, segment_ids=None, block_mask=None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool | None = None):
    """Pallas flash attention with automatic fallback to the XLA reference
    when shapes don't tile (same dispatch pattern as the reference's
    sdp_kernel selection, nn/functional/flash_attention.py).

    mask: additive float or bool (True=attend) attn mask, broadcastable to
    [b, 1|h, sq, sk] — streamed tile-wise into the kernel; key-padding
    forms ([*, *, 1, sk]) are lowered to an O(s) per-key bias.
    segment_ids: int [b, s] or (q_seg [b, sq], kv_seg [b, sk]) — varlen /
    packed-sequence masking with O(s) memory (attend iff ids equal).
    block_mask: int/bool [sq//block_q, sk//block_k] tile liveness —
    dead tiles' FLOPs are skipped entirely (block-sparse attention). The
    block mask must be IMPLIED by the elementwise masks (a tile marked
    dead must already be fully masked by mask/segments/causal), otherwise
    results diverge from the dense computation; callers like
    sparse_attention derive both from the same pattern."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    kbias = None
    if mask is not None:
        mask, kbias = _canon_mask(mask, b, h, sq, sk)
    qseg = kseg = None
    if segment_ids is not None:
        qseg, kseg = _canon_segments(segment_ids, b, sq, sk)
    if block_mask is not None:
        block_mask = jnp.asarray(block_mask, jnp.int32)
        if block_mask.shape != (sq // block_q, sk // block_k):
            raise ValueError(
                f"block_mask {block_mask.shape} != tile grid "
                f"({sq // block_q}, {sk // block_k})")
    if causal and sq > sk:
        # bottom-right alignment gives early queries ZERO visible keys —
        # handled by the masked-row guard, but parity with the XLA path is
        # simplest via the reference for this rare decode shape
        _log_fallback(q, k, block_q, block_k)
        return _reference(q, k, v, causal, scale, mask, kbias, qseg, kseg)
    if not _block_shapes_ok(q, k, block_q, block_k, v=v):
        _log_fallback(q, k, block_q, block_k)
        return _reference(q, k, v, causal, scale, mask, kbias, qseg, kseg)
    return _flash(q, k, v, mask, kbias, qseg, kseg, block_mask, causal,
                  scale, block_q, block_k, interpret)

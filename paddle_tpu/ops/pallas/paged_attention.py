"""Paged-decode attention as a Pallas TPU kernel.

Reference: the reference serves LLMs through a paged (block-table) KV
cache with a dedicated CUDA kernel behind
incubate/nn/functional/block_multihead_attention.py:33; the decode step
walks only the pages the block table names, never materializing the
per-sequence contiguous cache.

TPU design: one decode token per sequence attends over its pages via
**scalar-prefetch block indexing** — the block table and per-sequence
lengths ride in SMEM (pltpu.PrefetchScalarGridSpec), and each grid step's
BlockSpec index_map reads `table[b, j]` to DMA exactly that pool page into
VMEM. The [b, max_len, h, d] gather that the pre-kernel path built every
decode step (VERDICT r3 Missing #3) never exists: HBM traffic per step is
one read of the pages plus one [b, h, d] output write. Softmax is the
same fp32 online accumulation as the flash kernel, walking pages
left-to-right with running (m, l, acc) in VMEM scratch.

Layout: pools [num_blocks, block_size, h, d]; q [b, h, d] (t = 1);
block_table [b, pages_per_seq] int32; pos [b] int32 (keys <= pos visible,
masked_cache_attention semantics). Pages past a sequence's pos cost no
DMA: the kv index_map clamps the page index to the sequence's LAST LIVE
page, and the Pallas pipeline elides the block copy when consecutive grid
steps map to the same block — so a short sequence in a long max_len pool
pays only its own pages' bandwidth (the grid still iterates the dead
steps, but they are scalar no-ops: pl.when skips the FLOPs and the
revisited block is already resident in VMEM)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pragma: no cover - absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _paged_decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, block_size: int,
                         scale: float):
    """Grid (b, page): fold one KV page into this sequence's accumulators."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)
    h, d = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full((h, 1), NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((h, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((h, d), jnp.float32)

    pos = pos_ref[b]

    @pl.when(j * block_size <= pos)
    def _page():
        q = q_ref[0].astype(jnp.float32)          # [h, d]
        k = k_ref[0].astype(jnp.float32)          # [bs, h, d]
        v = v_ref[0].astype(jnp.float32)
        # scores[h, p] — contract d, batch h (bandwidth-bound: the page
        # read dominates, so the per-head small matmul shape is fine)
        s = jax.lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale    # [h, bs]
        idx = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        s = jnp.where(idx <= pos, s, NEG_INF)
        m = m_ref[:]
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - new_m))
        corr = jnp.exp(m - new_m)
        m_ref[:] = new_m
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)            # [h, d]

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_table, pos, scale=None,
                           interpret: bool | None = None):
    """One-token decode attention over a paged KV cache.

    q: [b, h, d]; pools: [num_blocks, block_size, h, d];
    block_table: [b, pages] int32; pos: scalar or [b] int32 (keys at
    index <= pos are visible). Returns [b, h, d]."""
    b, h, d = q.shape
    block_size = k_pool.shape[1]
    n_pages = block_table.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pos_arr = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))

    def kv_map(b, j, t, p):
        # clamp dead pages (j beyond pos) to the last live page: the
        # pipeline sees an unchanged block index and elides the DMA
        jc = jnp.minimum(j, p[b] // block_size)
        return (t[b, jc], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n_pages),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda b, j, t, p: (b, 0, 0)),
            pl.BlockSpec((1, block_size, h, d), kv_map),
            pl.BlockSpec((1, block_size, h, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda b, j, t, p: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, block_size=block_size,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), pos_arr, q, k_pool, v_pool)


def paged_decode_ok(h_dim: int) -> bool:
    """Kernel tiling gate: Mosaic needs the lane dim 8-aligned."""
    return h_dim % 8 == 0


def best_paged_impl(head_dim: int, n_heads: int, n_kv_heads: int,
                    q_len: int):
    """Which paged Pallas kernel can serve this attention shape.

    The dispatch gate for the serving runner (single source of truth, so
    model_runner and the tests can't drift): the specialized single-token
    MHA decode kernel above wins its exact shape; every other shape the
    ragged kernel covers — GQA (n_rep > 1), chunked prefill (q_len > 1),
    and mixed ragged batches. Returns "paged_decode" | "ragged" | None
    (None = no kernel tiles; callers fall back to the gather path)."""
    from paddle_tpu.ops.pallas.ragged_paged_attention import \
        ragged_attention_ok

    if q_len == 1 and n_heads == n_kv_heads and paged_decode_ok(head_dim):
        return "paged_decode"
    if ragged_attention_ok(head_dim, n_heads, n_kv_heads):
        return "ragged"
    return None

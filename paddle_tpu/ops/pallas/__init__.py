"""Pallas TPU kernels: flash attention, paged decode, ragged paged
attention. Imported lazily by the dispatch sites (models.generation,
serving.model_runner) so pure-CPU builds only pay for what they use."""

from paddle_tpu.ops.pallas.paged_attention import (  # noqa: F401
    best_paged_impl, paged_decode_attention, paged_decode_ok,
)
from paddle_tpu.ops.pallas.ragged_paged_attention import (  # noqa: F401
    attention_page_reads, ragged_attention_ok, ragged_paged_attention,
    ragged_reference,
)

__all__ = [
    "attention_page_reads", "best_paged_impl", "paged_decode_attention",
    "paged_decode_ok", "ragged_attention_ok", "ragged_paged_attention",
    "ragged_reference",
]

"""Ragged paged attention: one Pallas TPU kernel for the serving hot path.

Reference: "Ragged Paged Attention" (arXiv:2604.15464) — TPU serving
computes causal attention for a *ragged* batch of query spans (decode
steps with q_len=1, chunked-prefill spans with q_len=chunk at an offset,
and mixes of both) in a single kernel launch straight against the paged
KV pools. The reference's serving analogue is the CUDA kernel behind
incubate/nn/functional/block_multihead_attention.py; before this kernel
the serving engine's prefill chunks and GQA decodes took the
paged_gather + dense-mask path, materializing every sequence's ENTIRE
padded KV history ([B, max_pages*page_size, H, D]) in HBM per step.

Design (the flash-attention online-softmax structure of
ops/pallas/flash_attention.py crossed with the scalar-prefetch block
indexing of ops/pallas/paged_attention.py):

  * grid (batch, page): each step folds ONE pool page into one
    sequence's accumulators; per-sequence block tables, span start
    positions, and span lengths ride in SMEM via
    pltpu.PrefetchScalarGridSpec, and the K/V BlockSpec index_map reads
    ``table[b, j]`` to DMA exactly that pool page into VMEM;
  * ragged spans: sequence b computes query rows t in [0, q_len[b])
    standing at context positions start_pos[b] + t; rows past q_len are
    hard-masked and produce exact zeros (padded buckets never NaN), so
    one launch serves decode (q_len=1), prefill chunks (q_len=chunk,
    start_pos=chunk offset), and dead batch slots (q_len=0);
  * per-sequence early-out: pages wholly past a span's last visible key
    (j*page_size > start_pos + q_len - 1) run no FLOPs (pl.when) and
    cost no DMA — the index_map clamps dead page indices to the last
    live page and the Pallas pipeline elides the repeated block copy, so
    a short sequence in a long table pays only its own pages' bandwidth;
  * native GQA: q heads are grouped by their KV head OUTSIDE the kernel
    ([B, T, n_q, d] -> [B, n_kv, n_rep*T, d]), so the in-kernel matmuls
    batch over n_kv and contract d with no head replication — grouped
    models (n_rep > 1) stop falling back to the gather path;
  * fp32 online softmax with running (m, l, acc) in VMEM scratch across
    the page walk — the attention matrix never exists in HBM, and fully
    masked rows are guarded to exact zero output.

Layout: q [B, T, n_q_heads, d]; pools [num_pages, page_size, n_kv, d];
block_table [B, pages_per_seq] int32; start_pos/q_len [B] int32.
Causality is absolute-position based: query row t of sequence b sees
keys at positions <= start_pos[b] + t, i.e. masked_cache_attention
semantics — everything already written through the block table (earlier
chunks, shared prefix pages) plus this span's own causal triangle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pragma: no cover - absent on pure-CPU builds
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _ragged_kernel(table_ref, start_ref, qlen_ref, q_ref, k_ref, v_ref,
                   o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
                   n_rep: int, scale: float,
                   kscale_ref=None, vscale_ref=None):
    """Grid (b, page): fold one KV page into sequence b's span rows.

    With kscale_ref/vscale_ref (ISSUE 9: int8 pools), the K/V block is
    int8 codes and the per-page-per-head scales ride the SMEM scalar
    prefetch ([num_pages, n_kv] fp32, indexed by the SAME clamped page
    id the BlockSpec index_map DMA'd): the dequantize happens right
    here inside the page walk, and the online softmax stays fp32 — the
    page walk reads half the bytes, the math above it is unchanged."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)
    n_kv, G, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    T = G // n_rep                     # padded span rows per q head

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full((n_kv, G, 1), NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((n_kv, G, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((n_kv, G, d), jnp.float32)

    start = start_ref[b]
    qlen = qlen_ref[b]
    last_pos = start + qlen - 1        # last visible key position

    # early-out: dead spans (qlen == 0) and pages past the span's last
    # visible key fold nothing in — and their DMA was elided by the
    # clamped index_map (the revisited block is already VMEM-resident)
    @pl.when((qlen > 0) & (j * page_size <= last_pos))
    def _page():
        q = q_ref[0].astype(jnp.float32)           # [n_kv, G, d]
        k = k_ref[0].astype(jnp.float32)           # [ps, n_kv, d]
        v = v_ref[0].astype(jnp.float32)
        if kscale_ref is not None:
            # same clamp as the index_map: the page id whose block is
            # VMEM-resident right now; its scale row dequantizes it
            jc = jnp.minimum(j, jnp.maximum(last_pos, 0) // page_size)
            pid = table_ref[b, jc]
            ks = jnp.stack([kscale_ref[pid, h] for h in range(n_kv)])
            vs = jnp.stack([vscale_ref[pid, h] for h in range(n_kv)])
            k = k * ks[None, :, None]
            v = v * vs[None, :, None]
        # scores[n_kv, G, ps]: batch the KV-head dim, contract d — each
        # KV head serves its n_rep grouped query rows with no replication
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        # grouped row r is (rep, t) flattened; its query position is
        # start + t with t = r % T, and rows t >= qlen are padding
        t_idx = jax.lax.broadcasted_iota(
            jnp.int32, (n_kv, G, page_size), 1) % T
        k_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_kv, G, page_size), 2)
        s = jnp.where((k_pos <= start + t_idx) & (t_idx < qlen),
                      s, NEG_INF)
        m = m_ref[:]
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # masked-row guard: where every key so far is hard-masked, new_m
        # is still NEG_INF and exp(s - new_m) would be 1 — force 0 so the
        # row's l stays 0 and its output is exactly zero
        p = jnp.where(s <= NEG_INF * 0.5, 0.0, jnp.exp(s - new_m))
        corr = jnp.exp(m - new_m)
        m_ref[:] = new_m
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)    # [n_kv, G, d]

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pool, v_pool, block_table, start_pos, q_len,
                           scale=None, interpret: bool | None = None,
                           k_scale=None, v_scale=None):
    """Causal attention for a ragged batch of query spans over paged KV.

    q: [B, T, n_q_heads, d] — T is the PADDED span length (power-of-2
    bucket); pools: [num_pages, page_size, n_kv_heads, d];
    block_table: [B, pages_per_seq] int32; start_pos: [B] int32 (context
    position of each span's row 0); q_len: [B] int32 (live rows per
    span; 0 = dead slot). Query row t of sequence b attends keys at
    positions <= start_pos[b] + t. Rows past q_len output exact zeros.
    Returns [B, T, n_q_heads, d].

    Quantized pools (ISSUE 9): pass int8 code pools plus
    k_scale/v_scale [num_pages, n_kv_heads] fp32 (one scale per page
    per kv-head). The scales ride the SMEM scalar prefetch next to the
    block tables and each page tile is dequantized inside the page walk
    — HBM traffic is the int8 bytes + the scale rows, while the online
    softmax stays fp32.
    """
    B, T, n_q, d = q.shape
    page_size = k_pool.shape[1]
    n_kv = k_pool.shape[2]
    if n_q % n_kv:
        raise ValueError(f"n_q_heads={n_q} not a multiple of "
                         f"n_kv_heads={n_kv}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    quantized = k_scale is not None
    n_rep = n_q // n_kv
    n_pages = block_table.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    start_arr = jnp.broadcast_to(
        jnp.asarray(start_pos, jnp.int32).reshape(-1), (B,))
    qlen_arr = jnp.broadcast_to(
        jnp.asarray(q_len, jnp.int32).reshape(-1), (B,))
    G = n_rep * T
    # group q heads by KV head outside the kernel (XLA transpose) so the
    # kernel body needs no layout shuffles: row r of group g = (rep, t)
    qg = q.reshape(B, T, n_kv, n_rep, d).transpose(0, 2, 3, 1, 4)
    qg = qg.reshape(B, n_kv, G, d)

    def kv_map(b, j, t, s, ql, *_):
        # clamp dead pages (past the span's last visible key) to the last
        # live page: the pipeline sees an unchanged block index and
        # elides the DMA (dead slots clamp to the table's first entry)
        last = jnp.maximum(s[b] + ql[b] - 1, 0)
        jc = jnp.minimum(j, last // page_size)
        return (t[b, jc], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        # quantized pools prefetch the scale rows alongside the tables:
        # scalars 3/4 are k_scale/v_scale, read per clamped page id
        num_scalar_prefetch=5 if quantized else 3,
        grid=(B, n_pages),
        in_specs=[
            pl.BlockSpec((1, n_kv, G, d), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, d), kv_map),
            pl.BlockSpec((1, page_size, n_kv, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, n_kv, G, d),
                               lambda b, j, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_kv, G, 1), jnp.float32),
            pltpu.VMEM((n_kv, G, 1), jnp.float32),
            pltpu.VMEM((n_kv, G, d), jnp.float32),
        ],
    )
    if quantized:
        def kernel(table_ref, start_ref, qlen_ref, ks_ref, vs_ref, *rest):
            _ragged_kernel(table_ref, start_ref, qlen_ref, *rest,
                           page_size=page_size, n_rep=n_rep, scale=scale,
                           kscale_ref=ks_ref, vscale_ref=vs_ref)

        scalars = (block_table.astype(jnp.int32), start_arr, qlen_arr,
                   jnp.asarray(k_scale, jnp.float32),
                   jnp.asarray(v_scale, jnp.float32))
    else:
        kernel = functools.partial(_ragged_kernel, page_size=page_size,
                                   n_rep=n_rep, scale=scale)
        scalars = (block_table.astype(jnp.int32), start_arr, qlen_arr)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_kv, G, d),
                                       jnp.float32 if quantized else q.dtype),
        interpret=interpret,
    )(*scalars, qg, k_pool, v_pool)
    out = out.astype(q.dtype)
    out = out.reshape(B, n_kv, n_rep, T, d).transpose(0, 3, 1, 2, 4)
    return out.reshape(B, T, n_q, d)


def ragged_attention_ok(head_dim: int, n_q_heads: int,
                        n_kv_heads: int) -> bool:
    """Kernel tiling gate: Mosaic needs the lane dim 8-aligned, and GQA
    grouping needs the query heads to split evenly over the KV heads."""
    return head_dim % 8 == 0 and n_q_heads % max(1, n_kv_heads) == 0


def ragged_reference(q, k_pool, v_pool, block_table, start_pos, q_len,
                     scale=None, k_scale=None, v_scale=None):
    """Gather + dense-mask oracle with the kernel's exact output contract
    (padded rows and dead slots produce exact zeros). O(B * pages_per_seq
    * page_size) HBM — the path the kernel exists to retire; kept as the
    bit-level comparison target for tests and the CPU reference.

    With k_scale/v_scale (int8 pools, ISSUE 9) the gathered codes are
    dequantized with the SAME per-page-per-head scales the kernel reads
    — kernel-vs-reference comparisons stay exact in the int8 domain
    (both dequantize identical codes with identical scales)."""
    B, T, n_q, d = q.shape
    page_size = k_pool.shape[1]
    n_kv = k_pool.shape[2]
    n_rep = n_q // n_kv
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    kg = k_pool[block_table]             # [B, P, ps, n_kv, d]
    vg = v_pool[block_table]
    if k_scale is not None:
        ks = jnp.asarray(k_scale, jnp.float32)[block_table]  # [B, P, n_kv]
        vs = jnp.asarray(v_scale, jnp.float32)[block_table]
        kg = kg.astype(jnp.float32) * ks[:, :, None, :, None]
        vg = vg.astype(jnp.float32) * vs[:, :, None, :, None]
    L = kg.shape[1] * page_size
    kg = kg.reshape(B, L, n_kv, d)
    vg = vg.reshape(B, L, n_kv, d)
    if n_rep > 1:
        kg = jnp.repeat(kg, n_rep, axis=2)
        vg = jnp.repeat(vg, n_rep, axis=2)
    start = jnp.asarray(start_pos, jnp.int32).reshape(-1)
    qlen = jnp.asarray(q_len, jnp.int32).reshape(-1)
    qT = jnp.swapaxes(q, 1, 2).astype(jnp.float32)        # [B, nq, T, d]
    kT = jnp.swapaxes(kg, 1, 2).astype(jnp.float32)       # [B, nq, L, d]
    vT = jnp.swapaxes(vg, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhtd,bhLd->bhtL", qT, kT) * scale
    t_idx = jnp.arange(T, dtype=jnp.int32)
    q_pos = start[:, None] + t_idx[None, :]               # [B, T]
    k_pos = jnp.arange(L, dtype=jnp.int32)
    visible = ((k_pos[None, None, :] <= q_pos[:, :, None])
               & (t_idx[None, :, None] < qlen[:, None, None]))  # [B, T, L]
    s = jnp.where(visible[:, None], s, NEG_INF)
    row_live = jnp.any(s > NEG_INF * 0.5, axis=-1, keepdims=True)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(row_live, p, 0.0)
    out = jnp.einsum("bhtL,bhLd->bhtd", p, vT).astype(q.dtype)
    return jnp.swapaxes(out, 1, 2)


def attention_page_reads(start_pos, q_len, page_size: int):
    """Pages a ragged-kernel launch actually reads, per sequence: the
    clamped index_map DMAs pages [0, last_visible_page] and nothing for
    dead slots. Host-side analytics for the instrumented-pool counter —
    the CPU-countable half of the kernel's bandwidth claim."""
    start = np.asarray(start_pos, np.int64).reshape(-1)
    qlen = np.asarray(q_len, np.int64).reshape(-1)
    last = np.maximum(start + qlen - 1, 0)
    return np.where(qlen > 0, last // page_size + 1, 0)

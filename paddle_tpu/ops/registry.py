"""Op registry + eager dispatcher.

This file plays the role of the reference's generated dispatch stack:
  - paddle/phi/api/generator/api_gen.py  (C++ dispatch API from ops.yaml)
  - paddle/fluid/eager/auto_code_generator/generator/eager_gen.py
    (ad_func: AMP cast -> type promotion -> GradNode creation)
  - paddle/fluid/eager/auto_code_generator/generator/python_c_gen.py
    (_C_ops python bindings)

TPU-native shape: one generic dispatcher instead of per-op generated C++.
The per-op work is (1) AMP auto-cast per white/black lists, (2) a per-
(op, static-attrs) jit cache so each eager op executes as one compiled XLA
computation (the analogue of the reference's per-op phi kernels, with XLA
doing the tiling), (3) jax.vjp capture onto the autograd tape.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Any, Dict, List

import jax
import numpy as np
import yaml

from paddle_tpu.autograd import engine
from paddle_tpu.ops import impl as impl_mod
from paddle_tpu.utils import flags


class _Slot:
    """Placeholder for a tensor argument inside a hashable args template."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __hash__(self):
        return hash(("_Slot", self.i))

    def __eq__(self, other):
        return isinstance(other, _Slot) and other.i == self.i

    def __repr__(self):
        return f"<slot {self.i}>"


class OpDef:
    __slots__ = ("name", "impl", "diff", "dynamic", "rng", "method", "inplace")

    def __init__(self, name, impl, diff=True, dynamic=False, rng=False,
                 method=True, inplace=None):
        self.name = name
        self.impl = impl
        self.diff = diff
        self.dynamic = dynamic
        self.rng = rng
        self.method = method
        self.inplace = inplace


OPS: Dict[str, OpDef] = {}


def host_only_impl(name: str, api_hint: str):
    """Registry impl for host-side ops (NMS, graph sampling, decode loops)
    whose outputs are data-dependent-shaped and computed in numpy via the
    public python API. Generic dispatch (static replay, tracer replay)
    must never silently pass inputs through, so the registered impl
    raises, pointing at the real entry point."""
    def impl(*args, **kwargs):
        raise NotImplementedError(
            f"op '{name}' executes host-side with data-dependent output "
            f"shapes; call the python API ({api_hint}) directly — it is "
            "not replayable through generic op dispatch")
    return impl


def _load_yaml() -> None:
    path = os.path.join(os.path.dirname(__file__), "ops.yaml")
    with open(path) as f:
        spec = yaml.safe_load(f)
    for entry in spec["ops"]:
        name = entry["name"]
        fn = getattr(impl_mod, name)
        OPS[name] = OpDef(
            name,
            fn,
            diff=entry.get("diff", True),
            dynamic=entry.get("dynamic", False),
            rng=entry.get("rng", False),
            method=entry.get("method", True),
            inplace=entry.get("inplace"),
        )


def _template(obj, tensors: List[Any]):
    """Replace Tensors with _Slot placeholders (one level of list nesting)."""
    from paddle_tpu.core.tensor import Tensor

    if isinstance(obj, Tensor):
        tensors.append(obj)
        return _Slot(len(tensors) - 1)
    if isinstance(obj, (list, tuple)):
        return tuple(_template(e, tensors) for e in obj)
    return obj


def _fill(obj, vals):
    if isinstance(obj, _Slot):
        return vals[obj.i]
    if isinstance(obj, tuple):
        return tuple(_fill(e, vals) for e in obj)
    return obj


def _hashable(obj) -> bool:
    try:
        hash(obj)
        return True
    except TypeError:
        return False


@lru_cache(maxsize=8192)
def _jitted_fn(name: str, args_tpl, kwargs_tpl, cast_dtype,
               flags_version: int = 0):
    """Build + cache a jitted closure for (op, static attrs). jax.jit adds its
    own shape/dtype-keyed cache under this, so each distinct input signature
    compiles once — the eager-mode analogue of the reference's kernel cache."""
    op = OPS[name]

    def f(*tvals):
        if cast_dtype is not None:
            tvals = tuple(
                v.astype(cast_dtype)
                if hasattr(v, "dtype") and np.issubdtype(v.dtype, np.floating)
                else v
                for v in tvals
            )
        return op.impl(*_fill(args_tpl, tvals), **{k: _fill(v, tvals) for k, v in kwargs_tpl})

    return f, (jax.jit(f) if not op.dynamic else f)


# Incremented by static.program_guard / whenever symbolic tensors can exist;
# keeps the symbolic-input scan off the hot eager path entirely.
STATIC_SEEN = [False]


def _any_symbolic(obj) -> bool:
    from paddle_tpu.core.tensor import Tensor

    if isinstance(obj, Tensor):
        return type(obj._value).__name__ == "_Symbolic"
    if isinstance(obj, (list, tuple)):
        return any(_any_symbolic(e) for e in obj)
    return False


# api_tracer hook: when set, called as hook(name, args, kwargs) on every
# dispatch (reference python/paddle/api_tracer/ wraps each generated API;
# here ONE choke point sees them all)
TRACE_HOOK = [None]

# post-execution hook: when set, called as hook(name, outs) with every
# op's concrete outputs (amp.debugging tensor checker — reference
# python/paddle/amp/debugging.py over the check_nan_inf kernel hooks).
# Setting it disables tape-segment recording (outputs must be concrete to
# inspect), mirroring FLAGS_check_nan_inf. Never invoked inside a jit
# trace (outputs would be tracers).
CHECK_HOOK = [None]

# pre-execution stats hook (amp.debugging operator-stats collection):
# separate from TRACE_HOOK so the api_tracer's install/uninstall
# lifecycle and the stats collector's cannot corrupt each other
STATS_HOOK = [None]

# tape-segment recording state, owned here (the cheapest possible check on
# the dispatch hot path) but driven by paddle_tpu/jit/segments.py, which
# installs the recorder class on import and flips SEGMENT_MODE in its
# segment_mode() context manager
SEGMENT_MODE = [0]
SEGMENT_OPEN: List[Any] = [None]
SEGMENT_RECORDER_CLS: List[Any] = [None]

# in-jit-trace detection for the segment gate: ops dispatched while a jit
# trace is active (compiled children, functionalize.apply) must stage into
# THAT trace, never into the eager segment recorder. The trace context
# catches zero-tensor-input creation ops (ones/full/eye) that the
# tracer-valued-inputs sniff cannot see.
try:
    from jax._src.core import EvalTrace as _EvalTrace
    from jax._src.core import trace_ctx as _trace_ctx
except Exception:  # pragma: no cover - jax internals moved
    _EvalTrace = _trace_ctx = None


def _in_jit_trace(vals) -> bool:
    if _trace_ctx is not None:
        return not isinstance(_trace_ctx.trace, _EvalTrace)
    return any(isinstance(v, jax.core.Tracer) for v in vals)


def dispatch(name: str, args, kwargs, _op=None):
    """The generic ad_func (reference eager_gen.py:372 template).

    `_op`: an unregistered OpDef dispatched directly (no OPS entry) — used
    for one-shot closures like recompute segments, which would otherwise pin
    their captured function in the registry forever. Direct ops never use
    the name-keyed jit cache."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.amp.state import current_cast_dtype

    if TRACE_HOOK[0] is not None:
        TRACE_HOOK[0](name, args, kwargs)
    if STATS_HOOK[0] is not None:
        STATS_HOOK[0](name, args, kwargs)

    # static-graph build mode: ops on symbolic tensors record program nodes
    # (the reference's two-universe split, SURVEY.md §1 L5a/L5b). The flag
    # flips the first time a Program is created, so pure-eager users never
    # pay the tree walk.
    if STATIC_SEEN[0] and (
            _any_symbolic(args) or _any_symbolic(tuple(kwargs.values()))):
        from paddle_tpu.static.program import record_dispatch

        return record_dispatch(name, args, kwargs, _op=_op)

    op = _op if _op is not None else OPS[name]
    tensors: List[Tensor] = []
    if op.rng:
        from paddle_tpu.core.random import default_generator

        args = (args[0], default_generator.next_key()) + tuple(args[1:])
    args_tpl = _template(args, tensors)
    kwargs_items = tuple(sorted(kwargs.items()))
    kwargs_tpl = tuple((k, _template(v, tensors)) for k, v in kwargs_items)

    cast_dtype = current_cast_dtype(name)  # AMP O1 auto-cast (amp_lists)

    vals = [t._value for t in tensors]
    need_grad = (
        op.diff
        and engine.is_grad_enabled()
        and any(not t.stop_gradient for t in tensors)
    )

    # tape-segment recording (jit/segments.py): inside a segment_mode
    # context, stageable ops append to the open segment and return lazy
    # outputs; anything that can't stage (dynamic shapes, rng keys that
    # would bake into the cached executable, direct ops, unhashable attrs,
    # nan-checking) flushes the segment first so program order holds.
    if SEGMENT_MODE[0] and not _in_jit_trace(vals):
        recordable = (
            _op is None
            and not op.dynamic
            and not op.rng
            and _hashable(args_tpl)
            and _hashable(kwargs_tpl)
            and not flags.flag("FLAGS_check_nan_inf")
            and CHECK_HOOK[0] is None
        )
        if recordable:
            def seg_raw_f(*tvals):
                if cast_dtype is not None:
                    tvals = tuple(
                        v.astype(cast_dtype)
                        if hasattr(v, "dtype")
                        and np.issubdtype(v.dtype, np.floating)
                        else v
                        for v in tvals
                    )
                return op.impl(
                    *_fill(args_tpl, tvals),
                    **{k: _fill(v, tvals) for k, v in kwargs_tpl})

            if SEGMENT_OPEN[0] is None:
                SEGMENT_OPEN[0] = SEGMENT_RECORDER_CLS[0]()
            sig_key = (args_tpl, kwargs_tpl, cast_dtype)
            return SEGMENT_OPEN[0].record(
                name, seg_raw_f, sig_key, tensors, need_grad)
        if SEGMENT_OPEN[0] is not None:
            SEGMENT_OPEN[0].flush()
            vals = [t._value for t in tensors]  # flush rebinds lazy inputs

    use_jit = (
        flags.flag("FLAGS_eager_op_jit")
        and _op is None
        and not op.dynamic
        and _hashable(args_tpl)
        and _hashable(kwargs_tpl)
    )
    if use_jit:
        raw_f, fast_f = _jitted_fn(name, args_tpl, kwargs_tpl, cast_dtype,
                                   flags.flags_version())
    else:
        def raw_f(*tvals):
            if cast_dtype is not None:
                tvals = tuple(
                    v.astype(cast_dtype)
                    if hasattr(v, "dtype") and np.issubdtype(v.dtype, np.floating)
                    else v
                    for v in tvals
                )
            return op.impl(
                *_fill(args_tpl, tvals), **{k: _fill(v, tvals) for k, v in kwargs_tpl}
            )

        fast_f = raw_f

    if need_grad:
        out, vjp_fn = jax.vjp(fast_f if use_jit else raw_f, *vals)
    else:
        out = fast_f(*vals)

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    if flags.flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name, outs)
    if CHECK_HOOK[0] is not None and not _in_jit_trace(outs):
        CHECK_HOOK[0](name, outs)

    node = None
    if need_grad:
        float_out = any(_is_float_dtype(o.dtype) for o in outs)
        if float_out:
            node = engine.GradNode(
                name, vjp_fn, tensors, [(o.shape, o.dtype) for o in outs],
                multi_output=multi, raw_f=raw_f,
            )

    wrapped = []
    for i, o in enumerate(outs):
        t = Tensor._wrap(o)
        if node is not None and _is_float_dtype(o.dtype):
            t.stop_gradient = False
            t._grad_node = (node, i)
        wrapped.append(t)
    return tuple(wrapped) if multi else wrapped[0]


def _is_float_dtype(dt):
    import jax.numpy as jnp

    return jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating)


def _check_nan_inf(name, outs):
    """FLAGS_check_nan_inf analogue (reference new_executor/nan_inf_utils.cc)."""
    import jax.numpy as jnp

    for o in outs:
        if _is_float_dtype(o.dtype):
            if bool(jnp.any(~jnp.isfinite(o))):
                raise FloatingPointError(f"op {name} produced NaN/Inf output")


def make_op_function(name: str):
    def op_fn(*args, **kwargs):
        return dispatch(name, args, kwargs)

    op_fn.__name__ = name
    op_fn.__qualname__ = name
    op_fn.__doc__ = (OPS[name].impl.__doc__ or "") + "\n(Dispatched op; see ops.yaml)"
    return op_fn


_load_yaml()


def _getitem_impl(x, idx):
    return x[idx]


# basic-indexing view op (reference: kernels/stride/ as_strided family +
# pybind __getitem__ in eager_method.cc); advanced (array) indices fall back
# to the non-jit path via the hashability check.
OPS["_getitem"] = OpDef("_getitem", _getitem_impl, diff=True, method=False)


class _COps:
    """_C_ops-style namespace (reference python/paddle/_C_ops.py)."""

    def __init__(self):
        for name in OPS:
            setattr(self, name, make_op_function(name))

    def __getattr__(self, name):
        # ops registered after import (module-local OPS.setdefault calls)
        # resolve lazily
        if not name.startswith("_") and name in OPS:
            fn = make_op_function(name)
            setattr(self, name, fn)
            return fn
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")


C_OPS = _COps()

// Package paddle — Go bindings for the paddle_tpu inference C API.
//
// Reference: paddle/fluid/inference/goapi/ (config.go, predictor.go,
// tensor.go) over paddle_inference_c. This package wraps the same
// PD_* surface exported by paddle_tpu/csrc/capi.cpp (libpaddle_tpu_capi),
// so the reference's Go inference workflow ports by changing the linked
// library:
//
//	cfg := paddle.NewConfig()
//	cfg.SetModel("model.json", "model.params")
//	pred := paddle.NewPredictor(cfg)
//	in := pred.GetInputHandle(pred.GetInputNames()[0])
//	in.Reshape([]int32{1, 8})
//	in.CopyFromCpu(data)
//	pred.Run()
//	out := pred.GetOutputHandle(pred.GetOutputNames()[0])
//	out.CopyToCpu(result)
//
// Build: CGO_LDFLAGS="-L<repo>/build -lpaddle_tpu_capi" go build
// (this image carries no Go toolchain — the package is source-level
// parity, exercised via the same C symbols tests/test_capi.py drives
// from compiled C).
package paddle

/*
#cgo LDFLAGS: -lpaddle_tpu_capi
#include <stdint.h>
#include <stdlib.h>

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;
typedef struct PD_OneDimArrayCstr {
  size_t size;
  char** data;
} PD_OneDimArrayCstr;
typedef struct PD_OneDimArrayInt32 {
  size_t size;
  int32_t* data;
} PD_OneDimArrayInt32;

PD_Config* PD_ConfigCreate();
void PD_ConfigDestroy(PD_Config*);
void PD_ConfigSetModel(PD_Config*, const char*, const char*);
void PD_ConfigEnableLowPrecision(PD_Config*, const char*);
PD_Predictor* PD_PredictorCreate(PD_Config*);
void PD_PredictorDestroy(PD_Predictor*);
PD_OneDimArrayCstr* PD_PredictorGetInputNames(PD_Predictor*);
PD_OneDimArrayCstr* PD_PredictorGetOutputNames(PD_Predictor*);
PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor*, const char*);
PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor*, const char*);
int PD_PredictorRun(PD_Predictor*);
void PD_TensorDestroy(PD_Tensor*);
void PD_TensorReshape(PD_Tensor*, size_t, int32_t*);
void PD_TensorCopyFromCpuFloat(PD_Tensor*, const float*);
void PD_TensorCopyFromCpuInt64(PD_Tensor*, const int64_t*);
void PD_TensorCopyToCpuFloat(PD_Tensor*, float*);
void PD_TensorCopyToCpuInt64(PD_Tensor*, int64_t*);
PD_OneDimArrayInt32* PD_TensorGetShape(PD_Tensor*);
void PD_OneDimArrayInt32Destroy(PD_OneDimArrayInt32*);
*/
import "C"

import (
	"runtime"
	"unsafe"
)

// Config mirrors the reference goapi Config (config.go:43).
type Config struct {
	c *C.PD_Config
}

func NewConfig() *Config {
	cfg := &Config{c: C.PD_ConfigCreate()}
	runtime.SetFinalizer(cfg, func(c *Config) { C.PD_ConfigDestroy(c.c) })
	return cfg
}

// SetModel points at the serialized program + params produced by
// paddle_tpu.static.save_inference_model.
func (cfg *Config) SetModel(model, params string) {
	cm, cp := C.CString(model), C.CString(params)
	defer C.free(unsafe.Pointer(cm))
	defer C.free(unsafe.Pointer(cp))
	C.PD_ConfigSetModel(cfg.c, cm, cp)
}

// EnableLowPrecision selects the serving dtype ("bfloat16" / "int8") —
// the TPU analogue of EnableUseGpu+precision in the reference config.
func (cfg *Config) EnableLowPrecision(dtype string) {
	cd := C.CString(dtype)
	defer C.free(unsafe.Pointer(cd))
	C.PD_ConfigEnableLowPrecision(cfg.c, cd)
}

// Predictor mirrors goapi predictor.go.
type Predictor struct {
	p *C.PD_Predictor
}

func NewPredictor(cfg *Config) *Predictor {
	pred := &Predictor{p: C.PD_PredictorCreate(cfg.c)}
	runtime.SetFinalizer(pred, func(p *Predictor) {
		C.PD_PredictorDestroy(p.p)
	})
	return pred
}

func (p *Predictor) Run() bool {
	return C.PD_PredictorRun(p.p) == 0
}

func cstrArray(arr *C.PD_OneDimArrayCstr) []string {
	n := int(arr.size)
	out := make([]string, n)
	slice := unsafe.Slice(arr.data, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(slice[i])
	}
	return out
}

func (p *Predictor) GetInputNames() []string {
	return cstrArray(C.PD_PredictorGetInputNames(p.p))
}

func (p *Predictor) GetOutputNames() []string {
	return cstrArray(C.PD_PredictorGetOutputNames(p.p))
}

func (p *Predictor) GetInputHandle(name string) *Tensor {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	return newTensor(C.PD_PredictorGetInputHandle(p.p, cn))
}

func (p *Predictor) GetOutputHandle(name string) *Tensor {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	return newTensor(C.PD_PredictorGetOutputHandle(p.p, cn))
}

// Tensor mirrors goapi tensor.go.
type Tensor struct {
	t *C.PD_Tensor
}

func newTensor(ct *C.PD_Tensor) *Tensor {
	t := &Tensor{t: ct}
	runtime.SetFinalizer(t, func(t *Tensor) { C.PD_TensorDestroy(t.t) })
	return t
}

func (t *Tensor) Reshape(shape []int32) {
	C.PD_TensorReshape(t.t, C.size_t(len(shape)),
		(*C.int32_t)(unsafe.Pointer(&shape[0])))
}

func (t *Tensor) Shape() []int32 {
	arr := C.PD_TensorGetShape(t.t)
	defer C.PD_OneDimArrayInt32Destroy(arr)
	return append([]int32(nil),
		unsafe.Slice((*int32)(unsafe.Pointer(arr.data)),
			int(arr.size))...)
}

func (t *Tensor) CopyFromCpuFloat(data []float32) {
	C.PD_TensorCopyFromCpuFloat(t.t,
		(*C.float)(unsafe.Pointer(&data[0])))
}

func (t *Tensor) CopyFromCpuInt64(data []int64) {
	C.PD_TensorCopyFromCpuInt64(t.t,
		(*C.int64_t)(unsafe.Pointer(&data[0])))
}

func (t *Tensor) CopyToCpuFloat(data []float32) {
	C.PD_TensorCopyToCpuFloat(t.t, (*C.float)(unsafe.Pointer(&data[0])))
}

func (t *Tensor) CopyToCpuInt64(data []int64) {
	C.PD_TensorCopyToCpuInt64(t.t,
		(*C.int64_t)(unsafe.Pointer(&data[0])))
}

"""TDM (tree-based deep match) ops for the recommender world.

Reference: python/paddle/incubate/layers/nn.py tdm_child:488 /
tdm_sampler:583 over paddle/fluid/operators/tdm_child_op.h and
tdm_sampler_op.h.

TPU-native split: tdm_child is dense gather math (device-side,
jit-friendly). tdm_sampler draws per-layer negative samples — randomized,
data-dependent input-pipeline work that runs host-side on numpy, exactly
where the reference's CPU kernel runs it (there is no GPU tdm_sampler in
the reference either).

API difference from the reference (documented, deliberate): the tree
structures are passed as explicit arrays (tree_info / travel_list /
layer_list) instead of framework-created ParamAttr parameters — the
functional style of this framework; the array layouts match the reference
docs verbatim.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import OPS, OpDef, dispatch, host_only_impl


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


def _tdm_child(x, tree_info, child_nums=2, dtype="int32"):
    """tree_info: [node_nums, 3 + child_nums] rows =
    (item_id, layer_id, parent_id, child_0..child_{n-1}); child id 0 =
    padding. Returns (child [.., child_nums], leaf_mask same shape):
    leaf_mask=1 where the child exists AND is a leaf (its item_id != 0)."""
    ids = x.astype(jnp.int32)
    children = jnp.take(tree_info[:, 3:3 + child_nums], ids,
                        axis=0)                       # [..., child_nums]
    child_item = jnp.take(tree_info[:, 0], children)  # item_id of child
    leaf_mask = ((children != 0) & (child_item != 0)).astype(dtype)
    return children.astype(dtype), leaf_mask


OPS.setdefault("tdm_child", OpDef("tdm_child", _tdm_child, diff=False,
                                  method=False))
OPS.setdefault("tdm_sampler",
               OpDef("tdm_sampler",
                     host_only_impl("tdm_sampler",
                                    "paddle_tpu.incubate.tdm_sampler"),
                     diff=False, dynamic=True, method=False))


def tdm_child(x, tree_info, child_nums=2, dtype="int32", name=None):
    as_t = lambda v: v if isinstance(v, Tensor) else Tensor._wrap(
        jnp.asarray(v))
    return dispatch("tdm_child", (as_t(x), as_t(tree_info)),
                    {"child_nums": child_nums, "dtype": dtype})


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                travel_list=None, layer_list=None, output_positive=True,
                output_list=True, seed=0, dtype="int32", name=None):
    """Layer-wise negative sampling along each positive leaf's travel path.

    travel_list: [leaf_node_num, n_layers] — leaf's ancestor node id per
    layer (0-padded for unbalanced trees). layer_list: flat array of node
    ids, layer l occupying the slice after sum(layer_node_num_list[:l]).
    Returns (out, labels, mask), each [batch, sum(neg+pos per layer)] or
    per-layer lists when output_list=True. Padding rows (travel id 0)
    carry mask=0, like the reference's unbalanced-tree contract."""
    xv = _np(x).reshape(-1)
    travel = _np(travel_list)
    layer_flat = _np(layer_list).reshape(-1)
    n_layers = len(layer_node_num_list)
    rng = np.random.default_rng(seed or None)
    starts = np.cumsum([0] + list(layer_node_num_list))

    out_layers, lab_layers, mask_layers = [], [], []
    for li in range(n_layers):
        n_neg = int(neg_samples_num_list[li])
        width = n_neg + (1 if output_positive else 0)
        nodes = layer_flat[starts[li]:starts[li + 1]]
        if n_neg >= len(nodes):
            # reference UniformSampler contract: neg_samples_num must be
            # strictly less than the layer's node count (the positive is
            # excluded from the pool), else the op errors out
            raise ValueError(
                f"tdm_sampler: neg_samples_num_list[{li}]={n_neg} must be < "
                f"layer_node_num_list[{li}]={len(nodes)}")
        o = np.zeros((len(xv), width), np.int64)
        lab = np.zeros((len(xv), width), np.int64)
        msk = np.ones((len(xv), width), np.int64)
        for bi, leaf in enumerate(xv):
            pos = int(travel[int(leaf), li])
            if pos == 0:
                # unbalanced-tree padding layer: the reference kernel
                # (tdm_sampler_kernel.cc:136-154) zeroes the WHOLE row —
                # output, label and mask — no phantom negatives
                lab[bi, :] = 0
                msk[bi, :] = 0
                continue
            col = 0
            if output_positive:
                o[bi, 0] = pos
                lab[bi, 0] = 1
                col = 1
            # with-replacement draw excluding only the positive, matching
            # the reference UniformSampler distribution
            pool = nodes[nodes != pos]
            if n_neg:
                o[bi, col:col + n_neg] = rng.choice(pool, size=n_neg,
                                                    replace=True)
        out_layers.append(o)
        lab_layers.append(lab)
        mask_layers.append(msk)

    wrap = lambda a: Tensor._wrap(jnp.asarray(a.astype(dtype)))
    if output_list:
        return ([wrap(o) for o in out_layers],
                [wrap(l) for l in lab_layers],
                [wrap(m) for m in mask_layers])
    cat = lambda ls: np.concatenate(ls, axis=1)
    return (wrap(cat(out_layers)), wrap(cat(lab_layers)),
            wrap(cat(mask_layers)))

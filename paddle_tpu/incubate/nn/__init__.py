from paddle_tpu.incubate.nn import functional  # noqa: F401
from paddle_tpu.nn.layers import RMSNorm as FusedRMSNorm  # noqa: F401

from paddle_tpu.incubate.nn import functional  # noqa: F401
from paddle_tpu.nn.layers import RMSNorm as FusedRMSNorm  # noqa: F401

# ------------------ round-5: fused transformer layer surface ------------
# Reference python/paddle/incubate/nn/__init__.py — FusedLinear,
# FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
# FusedMultiTransformer, FusedDropoutAdd,
# FusedBiasDropoutResidualLayerNorm. The reference fuses these as single
# CUDA kernels; under XLA the SAME composition compiles into fused HLO
# (that is the one-compiler design), so these classes provide the API
# contract over the existing layers — the fusion itself is the
# compiler's.

from paddle_tpu.nn import Linear as FusedLinear  # noqa: E402,F401
from paddle_tpu.nn.layer import Layer as _Layer  # noqa: E402
from paddle_tpu.nn.transformer import (  # noqa: E402
    MultiHeadAttention as _MHA,
    TransformerEncoderLayer as _EncLayer,
)


class FusedMultiHeadAttention(_MHA):
    """Reference FusedMultiHeadAttention: attention + bias + dropout +
    residual + layer_norm in one op. XLA fuses the composition; the
    pre/post-LN + residual contract matches the reference."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        if (kdim is not None and kdim != embed_dim) or \
                (vdim is not None and vdim != embed_dim):
            raise NotImplementedError(
                "FusedMultiHeadAttention requires kdim == vdim == "
                "embed_dim (cross-dim projections not supported)")
        if need_weights:
            raise NotImplementedError(
                "FusedMultiHeadAttention need_weights=True is not "
                "supported")
        super().__init__(embed_dim, num_heads,
                         dropout=attn_dropout_rate)
        from paddle_tpu import nn as _nn

        self.normalize_before = normalize_before
        self.ln = _nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.out_dropout = _nn.Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        x = self.ln(query) if self.normalize_before else query
        out = super().forward(x, key, value, attn_mask=attn_mask)
        out = residual + self.out_dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(_Layer):
    """Reference FusedFeedForward: linear-act-dropout-linear-dropout +
    residual + LN."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from paddle_tpu import nn as _nn

        self.linear1 = _nn.Linear(d_model, dim_feedforward)
        self.linear2 = _nn.Linear(dim_feedforward, d_model)
        self.ln = _nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = _nn.Dropout(
            act_dropout_rate if act_dropout_rate is not None
            else dropout_rate)
        self.out_dropout = _nn.Dropout(dropout_rate)
        acts = {"relu": _nn.ReLU, "gelu": _nn.GELU,
                "silu": _nn.Silu, "swish": _nn.Silu}
        if activation not in acts:
            raise ValueError(f"unknown activation {activation!r} "
                             f"(one of {sorted(acts)})")
        self.activation = acts[activation]()
        self.normalize_before = normalize_before

    def forward(self, src, cache=None):
        residual = src
        x = self.ln(src) if self.normalize_before else src
        x = self.linear2(self.dropout(self.activation(self.linear1(x))))
        out = residual + self.out_dropout(x)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(_EncLayer):
    """Reference FusedTransformerEncoderLayer — same contract as
    nn.TransformerEncoderLayer; the 'fusion' is XLA's."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__(d_model, nhead, dim_feedforward,
                         dropout=dropout_rate, activation=activation,
                         attn_dropout=attn_dropout_rate,
                         act_dropout=act_dropout_rate,
                         normalize_before=normalize_before)


class FusedMultiTransformer(_Layer):
    """Reference FusedMultiTransformer: a stack of fused encoder layers
    driven by one call (the serving-path block stack)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, nranks=1,
                 ring_id=-1, name=None, **kw):
        super().__init__()
        from paddle_tpu import nn as _nn

        self.layers = _nn.LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kw):
        out = src
        for layer in self.layers:
            out = layer(out, attn_mask)
        return out


class FusedDropoutAdd(_Layer):
    """Reference FusedDropoutAdd: y = x + dropout(residual-path)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        from paddle_tpu import nn as _nn

        self.dropout = _nn.Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.dropout(x) + y


class FusedBiasDropoutResidualLayerNorm(_Layer):
    """Reference FusedBiasDropoutResidualLayerNorm:
    LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        from paddle_tpu import nn as _nn

        self.bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout = _nn.Dropout(dropout_rate)
        self.ln = _nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, residual):
        return self.ln(residual + self.dropout(x + self.bias))

"""Fused-op entry points (reference: python/paddle/incubate/nn/functional/).

On TPU these are XLA fusions or Pallas kernels of the registry ops — one
implementation serves both the stock and the "fused" API names.
"""

from paddle_tpu.ops.registry import C_OPS as _C

fused_rms_norm = _C.rms_norm
fused_layer_norm = _C.layer_norm
swiglu = _C.swiglu
fused_rotary_position_embedding = _C.rotary_embedding


def fused_multi_head_attention(q, k, v, causal=False, **kwargs):
    """Routes to the flash-attention path when shapes tile."""
    return _C.scaled_dot_product_attention(q, k, v, is_causal=causal)


def variable_length_memory_efficient_attention(q, k, v, *args, **kwargs):
    return _C.scaled_dot_product_attention(q, k, v, is_causal=True)


def fused_bias_act(x, bias=None, act_method="gelu"):
    out = x if bias is None else x + bias
    return getattr(_C, act_method)(out)


def fused_linear(x, weight, bias=None):
    return _C.linear(x, weight, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train"):
    return _C.dropout(x, p=p, training=training, mode=mode) + y

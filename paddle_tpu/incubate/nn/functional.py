"""Fused-op entry points (reference: python/paddle/incubate/nn/functional/).

On TPU these are XLA fusions or Pallas kernels of the registry ops — one
implementation serves both the stock and the "fused" API names.
"""

import jax

from paddle_tpu.ops.registry import C_OPS as _C

fused_rms_norm = _C.rms_norm
fused_layer_norm = _C.layer_norm
swiglu = _C.swiglu
fused_rotary_position_embedding = _C.rotary_embedding


def fused_multi_head_attention(q, k, v, causal=False, **kwargs):
    """Routes to the flash-attention path when shapes tile."""
    return _C.scaled_dot_product_attention(q, k, v, is_causal=causal)


def variable_length_memory_efficient_attention(q, k, v, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Variable-length attention: seq_lens/mask build a key-padding mask
    (reference incubate op semantics). Layout [b, s, h, d]."""
    attn_mask = None
    if mask is not None:
        attn_mask = mask
    elif kv_seq_lens is not None or seq_lens is not None:
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Tensor

        lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
        lv = lens._value if isinstance(lens, Tensor) else jnp.asarray(lens)
        lv = lv.reshape(-1)  # reference documents shape [batch, 1]
        sk = k.shape[1]
        valid = jnp.arange(sk)[None, :] < lv[:, None]        # [b, sk]
        attn_mask = Tensor._wrap(valid[:, None, None, :])    # [b, 1, 1, sk]
    return _C.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                           is_causal=causal, scale=scale)


def fused_bias_act(x, bias=None, act_method="gelu"):
    out = x if bias is None else x + bias
    return getattr(_C, act_method)(out)


def fused_linear(x, weight, bias=None):
    return _C.linear(x, weight, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train"):
    return _C.dropout(x, p=p, training=training, mode=mode) + y


def block_multihead_attention(q, k_pool, v_pool, block_table, pos,
                              scale=None):
    """Paged-KV decode attention (reference:
    python/paddle/incubate/nn/functional/block_multihead_attention.py).
    See models/generation.py for the cache layout."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.generation import (
        block_multihead_attention as _impl,
    )

    unwrap = lambda t: t._value if isinstance(t, Tensor) else t
    out = _impl(unwrap(q), unwrap(k_pool), unwrap(v_pool),
                unwrap(block_table), unwrap(pos), scale=scale)
    return Tensor._wrap(out) if isinstance(q, Tensor) else out


def masked_multihead_attention(x, cache_kv, pos, scale=None):
    """One-token decode attention over a dense [2, b, L, h, d] cache
    (reference incubate masked_multihead_attention: the non-paged serving
    kernel). x: [b, h*d] query input; pos: scalar or per-sequence [b]
    offsets of the current token."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.generation import masked_cache_attention

    unwrap = lambda t: t._value if isinstance(t, Tensor) else t
    xv, cache = unwrap(x), unwrap(cache_kv)
    k_cache, v_cache = cache[0], cache[1]
    b, L, h, d = k_cache.shape
    out = masked_cache_attention(xv.reshape(b, 1, h, d), k_cache, v_cache,
                                 unwrap(pos), scale=scale)
    out = out.reshape(b, h * d)
    return Tensor._wrap(out) if isinstance(x, Tensor) else out

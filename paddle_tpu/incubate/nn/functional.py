"""Fused-op entry points (reference: python/paddle/incubate/nn/functional/).

On TPU these are XLA fusions or Pallas kernels of the registry ops — one
implementation serves both the stock and the "fused" API names.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import C_OPS as _C

swiglu = _C.swiglu


def _unwrap(t):
    return t._value if isinstance(t, Tensor) else t


def _maybe_wrap(v, like):
    return Tensor._wrap(v) if isinstance(like, Tensor) else v


def _quantize(out, quant_scale, quant_round_type, quant_max_bound,
              quant_min_bound):
    """Emulation of the fused kernels' epilogue quant (int8 out)."""
    scaled = out.astype(jnp.float32) * quant_scale * quant_max_bound
    if quant_round_type == 0:
        rounded = jnp.rint(scaled)           # round half to even
    else:
        rounded = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)
    return jnp.clip(rounded, quant_min_bound, quant_max_bound).astype(
        jnp.int8)


def _bias_residual(x, bias, residual):
    """Shared pre-norm fusion: y = x (+ bias) (+ residual); y is also the
    residual_out the reference kernels return."""
    y = _unwrap(x)
    if bias is not None:
        y = y + _unwrap(bias)
    if residual is not None:
        y = y + _unwrap(residual)
    return y


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0):
    """Reference: incubate/nn/functional/fused_rms_norm.py —
    `fused_rms_norm(x, norm_weight, norm_bias, epsilon, begin_norm_axis,
    bias=None, residual=None, quant_*)`, returning `(out, residual_out)`
    (callers index `[0]`). Normalizes over the trailing axes starting at
    begin_norm_axis; bias/residual are added BEFORE the norm and the sum
    is returned as residual_out (the fused residual-add the kernel does
    in-flight). quant_scale > 0 enables the int8 epilogue."""
    y = _bias_residual(x, bias, residual)
    if begin_norm_axis < 0:
        begin_norm_axis += y.ndim
    axes = tuple(range(begin_norm_axis, y.ndim))
    yf = y.astype(jnp.float32)
    var = jnp.mean(jnp.square(yf), axis=axes, keepdims=True)
    out = (yf * jax.lax.rsqrt(var + epsilon)).astype(y.dtype)
    if norm_weight is not None:
        out = out * _unwrap(norm_weight).reshape(y.shape[begin_norm_axis:])
    if norm_bias is not None:
        out = out + _unwrap(norm_bias).reshape(y.shape[begin_norm_axis:])
    if quant_scale > 0:
        out = _quantize(out, quant_scale, quant_round_type,
                        quant_max_bound, quant_min_bound)
    return _maybe_wrap(out, x), _maybe_wrap(y, x)


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None,
                     quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                     quant_min_bound=0):
    """Reference: incubate/nn/functional/fused_layer_norm.py — same
    signature/return contract as fused_rms_norm, mean-centered norm."""
    y = _bias_residual(x, bias, residual)
    out = _unwrap(_C.layer_norm(
        _maybe_wrap(y, x), _maybe_wrap(_unwrap(norm_weight), x)
        if norm_weight is not None else None,
        _maybe_wrap(_unwrap(norm_bias), x) if norm_bias is not None
        else None, epsilon=epsilon, begin_norm_axis=begin_norm_axis))
    if quant_scale > 0:
        out = _quantize(out, quant_scale, quant_round_type,
                        quant_max_bound, quant_min_bound)
    return _maybe_wrap(out, x), _maybe_wrap(y, x)


def _rope_rotate(x, cos, sin, use_neox_rotary_style):
    if use_neox_rotary_style:
        # GPT-NeoX convention: rotate halves (matches ops.rotary_embedding)
        x1, x2 = jnp.split(x, 2, axis=-1)
        rot = jnp.concatenate([-x2, x1], axis=-1)
    else:
        # GPT-J convention: rotate even/odd interleaved pairs
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return (x * cos + rot * sin).astype(x.dtype)


def _rope_table(table, seq_len, head_dim, use_neox_rotary_style):
    """Normalize a user sin/cos table to [1, s, 1, d]. Accepts [s, d],
    [s, d/2], or the already-broadcastable [1, s, 1, d]."""
    t = _unwrap(table)
    t = t.reshape(t.shape[-2], t.shape[-1]) if t.ndim == 4 else t
    if t.shape[-1] == head_dim // 2:
        if use_neox_rotary_style:
            t = jnp.concatenate([t, t], axis=-1)
        else:
            t = jnp.repeat(t, 2, axis=-1)
    return t[None, :, None, :]


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False,
                                    rotary_emb_base=10000.0):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py
    — `(q, k, v, sin, cos, position_ids, use_neox_rotary_style,
    time_major, rotary_emb_base)`, returning the `(q, k, v)` tuple with
    None passed through. q/k/v: [b, s, h, d] ([s, b, h, d] when
    time_major); sin/cos: [s, d], [s, d/2] or [1, s, 1, d]; when absent
    they are built from rotary_emb_base. NOTE the argument order is
    sin-then-cos — the signature VERDICT r5 found the old alias
    rejecting."""
    qv = _unwrap(q)
    if time_major:
        swap = lambda t: None if t is None else jnp.swapaxes(_unwrap(t), 0, 1)
        qs, ks, vs = swap(q), swap(k), swap(v)
    else:
        qs = qv
        ks = None if k is None else _unwrap(k)
        vs = None if v is None else _unwrap(v)
    b, s, h, d = qs.shape
    if (sin is None) != (cos is None):
        raise ValueError("sin and cos must be given together")
    if cos is None:
        inv = 1.0 / (rotary_emb_base
                     ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
        freqs = jnp.outer(jnp.arange(s, dtype=jnp.float32), inv)  # [s, d/2]
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        cos_t = jnp.cos(emb)[None, :, None, :]
        sin_t = jnp.sin(emb)[None, :, None, :]
    else:
        cos_t = _rope_table(cos, s, d, use_neox_rotary_style)
        sin_t = _rope_table(sin, s, d, use_neox_rotary_style)
    if position_ids is not None:
        pid = _unwrap(position_ids)                      # [b, s]
        cos_t = jnp.take(cos_t[0, :, 0], pid, axis=0)[:, :, None, :]
        sin_t = jnp.take(sin_t[0, :, 0], pid, axis=0)[:, :, None, :]
    outs = []
    for t in (qs, ks, vs):
        if t is None:
            outs.append(None)
            continue
        o = _rope_rotate(t, cos_t, sin_t, use_neox_rotary_style)
        if time_major:
            o = jnp.swapaxes(o, 0, 1)
        outs.append(_maybe_wrap(o, q))
    return tuple(outs)


def fused_multi_head_attention(q, k, v, causal=False, **kwargs):
    """Routes to the flash-attention path when shapes tile."""
    return _C.scaled_dot_product_attention(q, k, v, is_causal=causal)


def variable_length_memory_efficient_attention(q, k, v, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    """Variable-length attention: seq_lens/mask build a key-padding mask
    (reference incubate op semantics). Layout [b, s, h, d]."""
    attn_mask = None
    if mask is not None:
        attn_mask = mask
    elif kv_seq_lens is not None or seq_lens is not None:
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Tensor

        lens = kv_seq_lens if kv_seq_lens is not None else seq_lens
        lv = lens._value if isinstance(lens, Tensor) else jnp.asarray(lens)
        lv = lv.reshape(-1)  # reference documents shape [batch, 1]
        sk = k.shape[1]
        valid = jnp.arange(sk)[None, :] < lv[:, None]        # [b, sk]
        attn_mask = Tensor._wrap(valid[:, None, None, :])    # [b, 1, 1, sk]
    return _C.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                           is_causal=causal, scale=scale)


def fused_bias_act(x, bias=None, act_method="gelu"):
    out = x if bias is None else x + bias
    return getattr(_C, act_method)(out)


def fused_linear(x, weight, bias=None):
    return _C.linear(x, weight, bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train"):
    return _C.dropout(x, p=p, training=training, mode=mode) + y


def block_multihead_attention(q, k_pool, v_pool, block_table, pos,
                              scale=None):
    """Paged-KV decode attention (reference:
    python/paddle/incubate/nn/functional/block_multihead_attention.py).
    See models/generation.py for the cache layout."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.generation import (
        block_multihead_attention as _impl,
    )

    unwrap = lambda t: t._value if isinstance(t, Tensor) else t
    out = _impl(unwrap(q), unwrap(k_pool), unwrap(v_pool),
                unwrap(block_table), unwrap(pos), scale=scale)
    return Tensor._wrap(out) if isinstance(q, Tensor) else out


def masked_multihead_attention(x, cache_kv, pos, scale=None):
    """One-token decode attention over a dense [2, b, L, h, d] cache
    (reference incubate masked_multihead_attention: the non-paged serving
    kernel). x: [b, h*d] query input; pos: scalar or per-sequence [b]
    offsets of the current token."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.generation import masked_cache_attention

    unwrap = lambda t: t._value if isinstance(t, Tensor) else t
    xv, cache = unwrap(x), unwrap(cache_kv)
    k_cache, v_cache = cache[0], cache[1]
    b, L, h, d = k_cache.shape
    out = masked_cache_attention(xv.reshape(b, 1, h, d), k_cache, v_cache,
                                 unwrap(pos), scale=scale)
    out = out.reshape(b, h * d)
    return Tensor._wrap(out) if isinstance(x, Tensor) else out

"""ASP — automatic structured (n:m) sparsity.

Reference: python/paddle/incubate/asp/ (utils.py mask algorithms:
get_mask_1d:192, get_mask_2d_greedy:334, get_mask_2d_best:452,
create_mask:508, check_sparsity:584; asp.py prune_model:319, decorate:233,
set_excluded_layers:55).

TPU note: the MXU has no 2:4 sparse-math path (that is an NVIDIA Ampere
sparse-tensor-core feature), so this module provides FORMAT parity — mask
calculation, pruning, and the sparsity-preserving optimizer wrapper are
semantically identical to the reference, the masked matmuls execute dense.
Mask correctness is what the tests pin.

Masks are computed host-side in numpy (pruning is an offline step in the
reference too); the per-step re-masking after optimizer.step() runs as
jitted elementwise multiplies on device.
"""

from __future__ import annotations

from enum import Enum
from functools import lru_cache
from itertools import permutations
from typing import Dict, Optional

import numpy as np

__all__ = [
    "MaskAlgo", "CheckMethod", "calculate_density",
    "get_mask_1d", "check_mask_1d",
    "get_mask_2d_greedy", "get_mask_2d_best", "check_mask_2d",
    "create_mask", "check_sparsity",
    "set_excluded_layers", "reset_excluded_layers",
    "prune_model", "decorate",
]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo) -> "CheckMethod":
        assert isinstance(mask_algo, MaskAlgo)
        return (CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D
                else CheckMethod.CHECK_2D)


def calculate_density(x) -> float:
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


def _pad_cols(mat: np.ndarray, m: int):
    """Zero-pad the trailing dim to a multiple of m; returns (groups, padded
    shape) where groups is (-1, m)."""
    rows, cols = mat.shape
    pad = (-cols) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((rows, pad), mat.dtype)], axis=1)
    return mat.reshape(-1, m), mat.shape


def get_mask_1d(mat, n: int, m: int):
    """Row-direction n:m mask: zero the n smallest |values| of every m
    consecutive elements (vectorized — no per-group python loop)."""
    mat = np.asarray(mat)
    groups, padded = _pad_cols(mat, m)
    order = np.argsort(np.abs(groups), axis=1)          # ascending
    mask = np.ones_like(groups)
    np.put_along_axis(mask, order[:, :n], 0, axis=1)
    return mask.reshape(padded)[:, : mat.shape[1]]


def check_mask_1d(mat, n: int, m: int) -> bool:
    """True iff every 1 x m group holds at least n zeros."""
    mat = np.asarray(mat)
    if mat.ndim <= 1:
        mat = mat.reshape(1, -1)
    groups, _ = _pad_cols(mat, m)
    return bool((np.count_nonzero(groups, axis=1) <= m - n).all())


def _pad_blocks(mat: np.ndarray, m: int):
    """Zero-pad both dims to multiples of m; returns (blocks [k, m, m],
    padded shape)."""
    r, c = mat.shape
    pr, pc = (-r) % m, (-c) % m
    if pr or pc:
        mat = np.pad(mat, ((0, pr), (0, pc)))
    R, C = mat.shape
    blocks = (mat.reshape(R // m, m, C // m, m)
              .transpose(0, 2, 1, 3).reshape(-1, m, m))
    return blocks, (R, C)


def _unpad_blocks(blocks: np.ndarray, padded, m: int, shape):
    R, C = padded
    out = (blocks.reshape(R // m, C // m, m, m)
           .transpose(0, 2, 1, 3).reshape(R, C))
    return out[: shape[0], : shape[1]]


def get_mask_2d_greedy(mat, n: int, m: int):
    """Per m x m block, keep entries in descending |value| order while no
    row or column exceeds n kept entries (2D n:m: >= n zeros per row AND
    per column of each block)."""
    mat = np.asarray(mat)
    blocks, padded = _pad_blocks(mat.astype(float), m)
    masks = np.zeros_like(blocks)
    for b in range(blocks.shape[0]):
        order = np.argsort(np.abs(blocks[b]), axis=None)[::-1]
        kept_r = np.zeros(m, np.int64)
        kept_c = np.zeros(m, np.int64)
        for flat in order:
            r, c = divmod(int(flat), m)
            if kept_r[r] < n and kept_c[c] < n:
                masks[b, r, c] = 1.0
                kept_r[r] += 1
                kept_c[c] += 1
    return _unpad_blocks(masks, padded, m, mat.shape)


@lru_cache(maxsize=16)
def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m 0/1 patterns with exactly n ones per row and at most n per
    column, as a [P, m, m] array."""
    row_choices = {p for p in permutations([1] * n + [0] * (m - n))}
    rows = [np.asarray(p, float) for p in row_choices]
    out = []

    def build(stack, colsum):
        if len(stack) == m:
            out.append(np.stack(stack))
            return
        for r in rows:
            ns = colsum + r
            if (ns <= n).all():
                build(stack + [r], ns)

    build([], np.zeros(m))
    return np.stack(out)


def get_mask_2d_best(mat, n: int, m: int):
    """Exhaustive-pattern 2D n:m mask maximizing the retained L1 norm
    (reference guarantees best >= greedy)."""
    mat = np.asarray(mat)
    blocks, padded = _pad_blocks(np.abs(mat.astype(float)), m)
    pats = _valid_2d_patterns(n, m)                     # [P, m, m]
    scores = np.einsum("kij,pij->kp", blocks, pats)
    best = pats[np.argmax(scores, axis=1)]              # [k, m, m]
    return _unpad_blocks(best, padded, m, mat.shape)


def check_mask_2d(mat, n: int, m: int) -> bool:
    """True iff every m x m block has >= n zeros in each row and column."""
    mat = np.asarray(mat)
    if mat.ndim <= 1:
        mat = mat.reshape(1, -1)
    blocks, _ = _pad_blocks(mat.astype(float), m)
    nz_rows = np.count_nonzero(blocks, axis=2)          # [k, m]
    nz_cols = np.count_nonzero(blocks, axis=1)
    return bool((nz_rows <= m - n).all() and (nz_cols <= m - n).all())


def _as_2d(t: np.ndarray):
    """Reference create_mask rank handling: rank<=3 flatten leading dims;
    rank-4 conv weights transpose to (h*w*out, in) — utils.py:564."""
    shape = t.shape
    if t.ndim == 1:
        return t.reshape(1, -1), None
    if t.ndim == 2:
        return t, None
    if t.ndim == 3:
        return t.reshape(shape[0] * shape[1], shape[2]), None
    if t.ndim == 4:
        tt = t.transpose(0, 1, 3, 2).reshape(
            shape[0] * shape[1] * shape[3], shape[2])
        def restore(mask):
            return (mask.reshape(shape[0], shape[1], shape[3], shape[2])
                    .transpose(0, 1, 3, 2))
        return tt, restore
    raise ValueError(
        f"ASP supports tensors of rank <= 4, got rank {t.ndim}")


def create_mask(tensor, func_name: MaskAlgo = MaskAlgo.MASK_1D,
                n: int = 2, m: int = 4):
    if not isinstance(func_name, MaskAlgo):
        raise AssertionError(
            f"func_name must be a MaskAlgo, got {type(func_name)}")
    t = np.asarray(tensor)
    dtype = t.dtype
    t2, restore = _as_2d(t.astype(float))
    mask = globals()[func_name.value](t2, n=n, m=m)
    if restore is not None:
        return restore(mask).astype(dtype)
    return mask.reshape(t.shape).astype(dtype)


def check_sparsity(tensor, func_name: CheckMethod = CheckMethod.CHECK_1D,
                   n: int = 2, m: int = 4) -> bool:
    if not isinstance(func_name, CheckMethod):
        raise AssertionError(
            f"func_name must be a CheckMethod, got {type(func_name)}")
    t = np.asarray(tensor).astype(float)
    if t.ndim >= 2:
        t, _ = _as_2d(t)
    return globals()[func_name.value](t, n=n, m=m)


# ------------------------------------------------------------- model pruning

_EXCLUDED: set = set()
# id(model) -> list of (param Tensor, device mask) pairs; decorate()d
# optimizers re-mask every recorded pair after each step
_MASK_PAIRS: Dict[int, list] = {}


def set_excluded_layers(param_names, main_program=None) -> None:
    """Exclude parameters (by name) from pruning (reference asp.py:55)."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None) -> None:
    _EXCLUDED.clear()


def _prunable(name: str, value) -> bool:
    """Reference supported_layer_list: weights of fc/linear/conv — here any
    rank>=2 non-excluded parameter whose trailing dim tiles by m=4."""
    if name in _EXCLUDED or any(name.endswith(f".{e}") for e in _EXCLUDED):
        return False
    if "bias" in name.rsplit(".", 1)[-1]:
        return False
    return value.ndim >= 2


def prune_model(model, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Prune a Layer's prunable parameters to the n:m pattern in place and
    (with_mask) remember the masks so `decorate`d optimizers keep the
    pattern through training (reference asp.py:319).

    mask_algo: 'mask_1d' | 'mask_2d_greedy' | 'mask_2d_best'."""
    import jax.numpy as jnp

    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    masks: Dict[str, object] = {}
    pairs = []
    for name, p in model.named_parameters():
        val = np.asarray(p._value)
        if not _prunable(name, val):
            continue
        mask = create_mask(val, func_name=algo, n=n, m=m)
        p._value = jnp.asarray(val * mask)
        dev_mask = jnp.asarray(mask.astype(val.dtype))
        masks[name] = dev_mask
        pairs.append((p, dev_mask))
    if with_mask:
        _MASK_PAIRS[id(model)] = pairs
        model._asp_mask_pairs = pairs   # keep alive with the model
    return masks


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer: after every step, re-apply the pruning masks so
    updated weights stay n:m sparse (reference asp.py:949 — the reference
    masks via fused momentum ops; masking the post-step weight is the same
    fixed point). Only masks belonging to THIS optimizer's parameters are
    applied — pruning model B must not let A's step re-zero B's weights."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._pairs = None

    def _my_pairs(self):
        if self._pairs is None:
            own = {id(p) for p in getattr(self._optimizer,
                                          "_parameter_list", [])}
            self._pairs = [
                (p, m) for pairs in _MASK_PAIRS.values()
                for p, m in pairs if not own or id(p) in own]
        return self._pairs

    def step(self):
        self._optimizer.step()
        for p, mask in self._my_pairs():
            p._value = p._value * mask

    def __getattr__(self, name):
        return getattr(self._optimizer, name)


def decorate(optimizer):
    """Return an optimizer whose step() preserves the pruned n:m pattern
    (reference asp.py:233)."""
    return OptimizerWithSparsityGuarantee(optimizer)

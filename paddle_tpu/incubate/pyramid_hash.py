"""pyramid_hash — n-gram hash embeddings for the PS/rec-sys world.

Reference: paddle/phi/kernels/cpu/pyramid_hash_kernel.cc (the last honest
op gap in rounds 2-3's coverage audit; yaml spec at
paddle/phi/ops/yaml/ops.yaml:3892).

Semantics (mirrored from the kernel):
  * input is a batch of variable-length int32 token sequences (LoD);
  * every sequence contributes its n-grams of lengths 2..pyramid_layer
    (layer `i` = grams of i+1 consecutive tokens);
  * each n-gram may be filtered (white list must contain it, black list
    must not) and — in training — dropped with drop_out_percent;
  * a surviving n-gram's num_emb-wide embedding is assembled chunk-wise:
    the gram's ids are cast to float32 and XXH32-hashed with a rolling
    seed schedule (0, rand_len, j + 2*rand_len, ...); each hash picks a
    rand_len-wide slice of the flat weight table (hash_embedding_ff,
    kernel.cc:39) — bit-exact XXH32 here, so positions match the
    reference for identical weights;
  * a sequence with no surviving n-grams yields one zero row;
  * outputs: (out [total_rows, num_emb], out_offsets [b+1],
    drop_pos, drop_pos_offsets).

Deviations (documented): the reference's white/black lists are raw
C-struct bloom-filter blobs; here they are python sets of id-tuples (same
filtering semantics, no binary-format dependency). Dropout uses numpy's
PCG instead of glibc rand_r — the decision distribution matches, the
exact stream does not.

The op is host-side by nature (LoD, data-dependent output shape — same
class as NMS/graph sampling); `w` gradients flow through a PyLayer that
scatter-adds each row's chunk gradients back to the hashed positions
(pyramid_hash_grad_kernel.cc).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

_P1 = 2654435761
_P2 = 2246822519
_P3 = 3266489917
_P4 = 668265263
_P5 = 374761393
_M = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M


def xxh32(data: bytes, seed: int = 0) -> int:
    """Bit-exact XXH32 (validated against the published test vectors)."""
    n = len(data)
    i = 0
    if n >= 16:
        a1 = (seed + _P1 + _P2) & _M
        a2 = (seed + _P2) & _M
        a3 = seed & _M
        a4 = (seed - _P1) & _M
        while i + 16 <= n:
            l1, l2, l3, l4 = struct.unpack_from("<IIII", data, i)
            a1 = (_rotl((a1 + l1 * _P2) & _M, 13) * _P1) & _M
            a2 = (_rotl((a2 + l2 * _P2) & _M, 13) * _P1) & _M
            a3 = (_rotl((a3 + l3 * _P2) & _M, 13) * _P1) & _M
            a4 = (_rotl((a4 + l4 * _P2) & _M, 13) * _P1) & _M
            i += 16
        h = (_rotl(a1, 1) + _rotl(a2, 7) + _rotl(a3, 12)
             + _rotl(a4, 18)) & _M
    else:
        h = (seed + _P5) & _M
    h = (h + n) & _M
    while i + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, i)
        h = (_rotl((h + lane * _P3) & _M, 17) * _P4) & _M
        i += 4
    while i < n:
        h = (_rotl((h + data[i] * _P5) & _M, 11) * _P1) & _M
        i += 1
    h ^= h >> 15
    h = (h * _P2) & _M
    h ^= h >> 13
    h = (h * _P3) & _M
    h ^= h >> 16
    return h


def _gram_positions(gram_f32: np.ndarray, num_emb: int, rand_len: int,
                    space_len: int) -> List[int]:
    """The rolling-seed position schedule of hash_embedding_ff: chunk j
    reads weights[pos_j : pos_j + rand_len] with pos list (h(0), h(rand),
    h(2*rand), h(rand + 2*rand), ...)."""
    raw = gram_f32.tobytes()
    pos1 = xxh32(raw, 0) % space_len
    pos2 = xxh32(raw, rand_len) % space_len
    out = []
    for j in range(0, num_emb, rand_len):
        pos3 = xxh32(raw, j + 2 * rand_len) % space_len
        out.append(pos1)
        pos1, pos2 = pos2, pos3
    return out


def _as_sequences(x, lod=None) -> List[np.ndarray]:
    if lod is not None:
        flat = np.asarray(getattr(x, "_value", x)).reshape(-1)
        off = np.asarray(lod, np.int64).reshape(-1)
        return [flat[off[i]:off[i + 1]] for i in range(len(off) - 1)]
    return [np.asarray(getattr(s, "_value", s)).reshape(-1) for s in x]


def pyramid_hash(x, w, white_list: Optional[Set[tuple]] = None,
                 black_list: Optional[Set[tuple]] = None, *,
                 num_emb: int, space_len: int, pyramid_layer: int = 2,
                 rand_len: int = 16, drop_out_percent: float = 0.0,
                 is_training: bool = False, use_filter: bool = True,
                 seed: int = 0, lod=None):
    """See module docstring. x: list of int sequences (or flat + lod
    offsets); w: flat weight Tensor of length >= space_len + rand_len.
    Returns (out Tensor [total, num_emb], out_offsets np.int64 [b+1],
    drop_pos np.int32, drop_pos_offsets np.int64)."""
    import jax.numpy as jnp

    from paddle_tpu.autograd.py_layer import PyLayer
    from paddle_tpu.core.tensor import Tensor

    if num_emb % rand_len:
        raise ValueError(f"num_emb {num_emb} must be a multiple of "
                         f"rand_len {rand_len}")
    seqs = _as_sequences(x, lod)
    w_t = w if isinstance(w, Tensor) else Tensor._wrap(jnp.asarray(w))
    w_flat = np.asarray(w_t._value).reshape(-1)
    if w_flat.size < space_len + rand_len:
        raise ValueError(
            f"weight table of {w_flat.size} elements cannot serve "
            f"space_len {space_len} + rand_len {rand_len}")
    rng = np.random.default_rng(seed or None)

    kept_positions: List[List[int]] = []   # per kept n-gram
    out_offsets = [0]
    drop_flags: List[int] = []
    # NB: mirroring the reference contract exactly (pyramid_hash_kernel.cc
    # drop_pos_offset): drop_flags holds one entry per CANDIDATE gram,
    # while drop_offsets accumulate KEPT counts — the offsets partition
    # the output rows, not the flag array.
    drop_offsets = [0]
    kept_total = 0
    zero_rows: List[int] = []              # row indices that stay zero
    for s in seqs:
        ww = len(s)
        kept_here = 0
        if ww >= 2:
            for ilayer in range(1, min(pyramid_layer, ww)):
                for l in range(ww - ilayer):
                    gram = tuple(int(v) for v in s[l:l + ilayer + 1])
                    ok = True
                    if use_filter:
                        if white_list is not None and gram not in white_list:
                            ok = False
                        if black_list is not None and gram in black_list:
                            ok = False
                    if not ok:
                        drop_flags.append(0)
                        continue
                    if is_training and drop_out_percent > 0.0 \
                            and rng.random() < drop_out_percent:
                        drop_flags.append(0)
                        continue
                    drop_flags.append(1)
                    gram_f32 = np.asarray(gram, np.float32)
                    kept_positions.append(_gram_positions(
                        gram_f32, num_emb, rand_len, space_len))
                    kept_here += 1
        kept_total += kept_here
        drop_offsets.append(kept_total)
        if kept_here == 0:
            zero_rows.append(out_offsets[-1])
            out_offsets.append(out_offsets[-1] + 1)
            kept_positions.append(None)    # placeholder zero row
        else:
            out_offsets.append(out_offsets[-1] + kept_here)

    total = out_offsets[-1]
    # gather index matrix [total, num_emb]: chunk c of row r reads
    # w_flat[pos + 0..rand_len); zero rows read index 0 and mask to 0
    idx = np.zeros((total, num_emb), np.int64)
    mask = np.ones((total, 1), np.float32)
    for r, poss in enumerate(kept_positions):
        if poss is None:
            mask[r] = 0.0
            continue
        for c, p in enumerate(poss):
            idx[r, c * rand_len:(c + 1) * rand_len] = np.arange(
                p, p + rand_len)

    class _PyramidGather(PyLayer):
        @staticmethod
        def forward(ctx, w_tensor):
            ctx.save_for_backward(w_tensor)
            vals = jnp.take(w_tensor._value.reshape(-1), jnp.asarray(idx))
            return Tensor._wrap(vals * jnp.asarray(mask))

        @staticmethod
        def backward(ctx, grad_out):
            (w_tensor,) = ctx.saved_tensor()
            flat_g = jnp.zeros((w_flat.size,), grad_out._value.dtype)
            g = grad_out._value * jnp.asarray(mask)
            flat_g = flat_g.at[jnp.asarray(idx).reshape(-1)].add(
                g.reshape(-1))
            return Tensor._wrap(
                flat_g.reshape(np.asarray(w_tensor._value).shape))

    out = _PyramidGather.apply(w_t)
    return (out, np.asarray(out_offsets, np.int64),
            np.asarray(drop_flags, np.int32),
            np.asarray(drop_offsets, np.int64))


def _register():
    from paddle_tpu.ops.registry import OPS, OpDef, host_only_impl

    OPS.setdefault("pyramid_hash", OpDef(
        "pyramid_hash",
        host_only_impl("pyramid_hash",
                       "paddle_tpu.incubate.pyramid_hash.pyramid_hash"),
        diff=False, dynamic=True, method=False))


_register()

"""paddle.incubate — fused LLM ops + experimental features.

Reference: python/paddle/incubate/ (nn/functional fused ops:
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_moe,
block_multihead_attention, masked_multihead_attention; asp; optimizers).

TPU-native: these "fused kernels" are either XLA fusions of the stock impls
(rms_norm, swiglu — XLA fuses the chains into single kernels) or the Pallas
flash-attention path; the incubate namespace provides the reference's entry
points over the same registry ops.
"""

from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate.pyramid_hash import pyramid_hash  # noqa: F401
from paddle_tpu.incubate.tdm import tdm_child, tdm_sampler  # noqa: F401


def __getattr__(name):
    # lazy: paddle.incubate.multiprocessing — registering ForkingPickler
    # reductions has import-order side effects, so only load on demand
    if name == "multiprocessing":
        import paddle_tpu.multiprocessing as mp

        return mp
    raise AttributeError(name)

"""paddle.incubate — fused LLM ops + experimental features.

Reference: python/paddle/incubate/ (nn/functional fused ops:
fused_rms_norm, fused_rotary_position_embedding, swiglu, fused_moe,
block_multihead_attention, masked_multihead_attention; asp; optimizers).

TPU-native: these "fused kernels" are either XLA fusions of the stock impls
(rms_norm, swiglu — XLA fuses the chains into single kernels) or the Pallas
flash-attention path; the incubate namespace provides the reference's entry
points over the same registry ops.
"""

from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate.pyramid_hash import pyramid_hash  # noqa: F401
from paddle_tpu.incubate.tdm import tdm_child, tdm_sampler  # noqa: F401


def __getattr__(name):
    # lazy: paddle.incubate.multiprocessing — registering ForkingPickler
    # reductions has import-order side effects, so only load on demand
    if name == "multiprocessing":
        import paddle_tpu.multiprocessing as mp

        return mp
    raise AttributeError(name)


# ---------------------- round-5: reference incubate __all__ completion --
# (reference python/paddle/incubate/__init__.py)

from paddle_tpu.geometric import (  # noqa: E402,F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from paddle_tpu.optimizer import LookAhead  # noqa: E402,F401


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Reference incubate.graph_send_recv -> geometric.send_u_recv."""
    from paddle_tpu.geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    from paddle_tpu.geometric import khop_sampler

    return khop_sampler(row, colptr, input_nodes, sample_sizes,
                        sorted_eids=sorted_eids, return_eids=return_eids)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from paddle_tpu.geometric import reindex_graph

    return reindex_graph(x, neighbors, count)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from paddle_tpu.geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def identity_loss(x, reduction="none"):
    """Reference incubate.identity_loss: marks x as a loss (IPU
    pipeline hint); numerically reduce-or-identity."""
    if reduction in ("mean", 1):
        return x.mean()
    if reduction in ("sum", 0):
        return x.sum()
    return x


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (reference incubate.softmax_mask_fuse —
    one XLA fusion here, which is the point of the op)."""
    import paddle_tpu as paddle

    return paddle.nn.functional.softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with the causal upper-triangle mask fused (reference
    softmax_mask_fuse_upper_triangle)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    s = x.shape[-1]
    causal = jnp.where(jnp.tril(jnp.ones((s, s), bool)), 0.0, -1e30)
    return paddle.nn.functional.softmax(
        x + Tensor._wrap(causal.astype(jnp.float32)), axis=-1)


class ModelAverage:
    """Reference incubate.ModelAverage: maintains a running average of the
    parameters for EVALUATION — step() only updates the average (the live
    training weights are never touched); apply() swaps the averages in
    (backing up the live values), restore() swaps back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        import jax.numpy as jnp

        self._params = list(parameters or [])
        self._jnp = jnp
        self._avg = [jnp.array(p._value, dtype=jnp.float32, copy=True)
                     for p in self._params]
        self._n = 1
        self._backup = None

    def step(self):
        self._n += 1
        mu = 1.0 / self._n
        self._avg = [a + mu * (p._value.astype(self._jnp.float32) - a)
                     for a, p in zip(self._avg, self._params)]

    def apply(self, executor=None, need_restore=True):
        self._backup = [self._jnp.array(p._value, copy=True)
                        for p in self._params]
        for p, a in zip(self._params, self._avg):
            p._inplace_update(a.astype(p._value.dtype))
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        if self._backup is None:
            raise RuntimeError("ModelAverage.restore() without a prior "
                               "apply(need_restore=True)")
        for p, b in zip(self._params, self._backup):
            p._inplace_update(b)
        self._backup = None

    def minimize(self, loss):   # reference-compatible no-op: the inner
        pass                    # optimizer owns the update here


from paddle_tpu import inference  # noqa: E402,F401

"""paddle.Model — the high-level train/eval/predict engine.

Reference: python/paddle/hapi/model.py:1472.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.metric import Metric
from paddle_tpu.nn.layer import Layer


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._use_jit = True
        self.stop_training = False

    # ------------------------------------------------------------- prepare

    def prepare(self, optimizer=None, loss=None, metrics=None, jit=True,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        self._use_jit = jit
        self._amp_level = None
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level")
        return self

    # ------------------------------------------------------------- steps

    def _ensure_train_step(self):
        if self._train_step is None and self._use_jit:
            from paddle_tpu.jit import TrainStep

            self._train_step = TrainStep(
                self.network, lambda out, *labels: self._loss(out, *labels),
                self._optimizer, amp_level=self._amp_level)
        return self._train_step

    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self._use_jit:
            step = self._ensure_train_step()
            loss = step(*inputs, *labels)
            return [float(loss)]
        out = self.network(*inputs)
        loss = self._loss(out, *labels)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return [float(loss)]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        self._sync()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        with paddle.no_grad():
            out = self.network(*inputs)
            loss = self._loss(out, *labels) if self._loss else None
        metrics = []
        for m in self._metrics:
            res = m.update(m.compute(out, *labels)) if hasattr(m, "compute") \
                else m.update(out, *labels)
            metrics.append(res)
        return ([float(loss)] if loss is not None else []), metrics

    def predict_batch(self, inputs):
        self.network.eval()
        self._sync()
        with paddle.no_grad():
            out = self.network(*_to_list(inputs))
        return out

    def _sync(self):
        if self._train_step is not None:
            self._train_step.sync()

    # ------------------------------------------------------------- loops

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None):
        from paddle_tpu.hapi.callbacks import CallbackList, ProgBarLogger

        loader = _as_loader(train_data, batch_size, shuffle, drop_last,
                            num_workers)
        eval_loader = (_as_loader(eval_data, batch_size, False, False, 0)
                       if eval_data is not None else None)
        cbks = CallbackList((callbacks or []) +
                            ([ProgBarLogger(log_freq)] if verbose else []))
        cbks.set_model(self)
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                inputs, labels = _split_batch(batch)
                cbks.on_train_batch_begin(step)
                losses = self.train_batch(inputs, labels)
                logs = {"loss": losses[0], "step": step, "epoch": epoch}
                cbks.on_train_batch_end(step, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = _split_batch(batch)
            loss, _ = self.eval_batch(inputs, labels)
            losses.extend(loss)
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outputs = []
        for batch in loader:
            # datasets commonly yield (input, label) even at predict time;
            # drop the trailing label like the reference's input-spec split
            inputs, _ = _split_batch(batch, has_labels=isinstance(
                batch, (list, tuple)) and len(batch) >= 2)
            outputs.append(self.predict_batch(inputs))
        if stack_outputs:
            if outputs and isinstance(outputs[0], (tuple, list)):
                n_out = len(outputs[0])
                return [Tensor._wrap(np.concatenate(
                    [o[i].numpy() for o in outputs])) for i in range(n_out)]
            return [Tensor._wrap(np.concatenate(
                [o.numpy() for o in outputs]))]
        return outputs

    # ------------------------------------------------------------- io

    def save(self, path, training=True):
        self._sync()
        paddle.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            paddle.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = paddle.load(path + ".pdparams")
        self.network.set_state_dict(state)
        self._train_step = None  # rebuild with fresh params
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(paddle.load(path + ".pdopt"))
        return self

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from paddle_tpu.hapi.summary import summary

        return summary(self.network, input_size, dtypes=dtype)


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _split_batch(batch, has_labels=True):
    if isinstance(batch, (list, tuple)):
        if has_labels and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []
    return [batch], []


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data

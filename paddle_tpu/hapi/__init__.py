"""High-level API: paddle.Model + callbacks + summary.

Reference: python/paddle/hapi/model.py:1472 (Model.fit:2200 / evaluate /
predict), hapi/callbacks.py (ProgressBar, ModelCheckpoint, EarlyStopping,
LRScheduler), hapi/model_summary.py (paddle.summary).

TPU-native: Model.prepare(jit=True) (default) trains through the compiled
TrainStep — the whole fit loop runs one XLA executable per batch with donated
state, instead of the reference's per-op eager dispatch.
"""

from paddle_tpu.hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRSchedulerCallback, ModelCheckpoint, ProgBarLogger,
)
from paddle_tpu.hapi.model import Model  # noqa: F401
from paddle_tpu.hapi.summary import summary  # noqa: F401

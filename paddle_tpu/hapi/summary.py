"""paddle.summary — model summary table.

Reference: python/paddle/hapi/model_summary.py. Uses jax.eval_shape so no
device compute happens (the reference runs a real forward).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer import Layer


def summary(net: Layer, input_size, dtypes=None):
    """Prints a per-layer table; returns {'total_params', 'trainable_params'}."""
    if isinstance(input_size, (tuple, list)) and input_size and isinstance(
            input_size[0], (list, tuple)):
        sizes = [tuple(s) for s in input_size]
    else:
        sizes = [tuple(input_size)]
    dtypes = dtypes or ["float32"] * len(sizes)

    records = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            n_params = sum(int(np.prod(p.shape))
                           for p in l._parameters.values() if p is not None)
            records.append((name, type(l).__name__,
                            list(getattr(out, "shape", [])), n_params))

        return hook

    for name, layer in net.named_sublayers(include_self=False):
        if not layer._sub_layers:  # leaves only
            hooks.append(layer.register_forward_post_hook(
                make_hook(name, layer)))

    was_training = net.training
    net.eval()
    try:
        inputs = [paddle.zeros(list(s), dtype=d)
                  for s, d in zip(sizes, dtypes)]
        with paddle.no_grad():
            net(*inputs)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if p.trainable)

    line = "-" * 72
    print(line)
    print(f"{'Layer (type)':<34}{'Output Shape':<22}{'Param #':>12}")
    print(line)
    for name, cls, shape, n in records:
        print(f"{name + ' (' + cls + ')':<34}{str(shape):<22}{n:>12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}

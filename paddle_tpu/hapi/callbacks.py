"""Training callbacks. Reference: python/paddle/hapi/callbacks.py."""

from __future__ import annotations

import time
from typing import List, Optional


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        self._steps = 0

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose and step % self.log_freq == 0:
            loss = (logs or {}).get("loss")
            print(f"Epoch {self._epoch} step {step}: loss="
                  f"{loss:.4f}" if loss is not None else "")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            extra = " ".join(f"{k}={v:.4f}" for k, v in (logs or {}).items()
                             if isinstance(v, float))
            print(f"Epoch {epoch} done in {dt:.1f}s {extra}")


class ModelCheckpoint(Callback):
    """Reference: hapi/callbacks.py ModelCheckpoint."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    """Reference: hapi/callbacks.py EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True,
                 save_dir=None):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.save_best_model = save_best_model
        self.save_dir = save_dir
        if mode == "max" or (mode == "auto" and ("acc" in monitor or
                                                 "auc" in monitor)):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf") if baseline is None else baseline
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf") if baseline is None else baseline
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir:
                self.model.save(f"{self.save_dir}/best_model")
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True


class LRSchedulerCallback(Callback):
    """Steps the optimizer's LRScheduler each epoch (by_step=False) or each
    batch (by_step=True). Reference: hapi/callbacks.py LRScheduler."""

    def __init__(self, by_step=False, by_epoch=True):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = self.model._optimizer
        return getattr(opt, "_lr_scheduler", None)

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Shrink the LR when a monitored metric stops improving (reference
    hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        if mode == "max" or (mode == "auto" and ("acc" in monitor
                                                 or "auc" in monitor)):
            self.better = lambda c, b: c > b + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda c, b: c < b - self.min_delta
            self.best = float("inf")
        self.wait = 0
        self.cooldown_counter = 0

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            return
        if self.cooldown_counter > 0:
            # in cooldown: the LR just changed — don't count this epoch
            # toward patience (Keras/reference semantics)
            self.cooldown_counter -= 1
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            opt = self.model._optimizer
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"[ReduceLROnPlateau] epoch {epoch}: "
                          f"lr {old:.2e} -> {new:.2e}")
            self.cooldown_counter = self.cooldown
            self.wait = 0


class VisualDL(Callback):
    """Scalar logging callback (reference hapi/callbacks.py VisualDL).
    The VisualDL service itself needs egress; this writer emits the same
    per-step/per-epoch scalars as JSONL under log_dir, which the real
    VisualDL (or anything else) can ingest offline."""

    def __init__(self, log_dir="vdl_log"):
        self.log_dir = log_dir
        self._fh = None

    def _write(self, kind, step, logs):
        import json
        import os

        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"),
                            "a")
        rec = {"kind": kind, "step": step}
        rec.update({k: float(v) for k, v in (logs or {}).items()
                    if isinstance(v, (int, float))})
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._write("batch", step, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._write("epoch", epoch, logs)

    def on_train_end(self, logs=None):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class LRScheduler(Callback):
    """Per-epoch/step LR scheduler stepping callback (reference
    callbacks.LRScheduler)."""

    def __init__(self, by_step=False, by_epoch=True):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class WandbCallback(Callback):
    """Weights & Biases logging (reference callbacks.WandbCallback).
    wandb is not bundled on this box; the callback degrades to an
    in-memory log (self.history) and raises only if the user explicitly
    requires the backend (project given AND wandb importable check
    fails... no: stays silent-local, zero-egress box)."""

    def __init__(self, project=None, name=None, **kwargs):
        self.project = project
        self.run_name = name
        self.history = []
        try:
            import wandb  # noqa: F401 — optional dependency

            self._wandb = wandb
        except ImportError:
            self._wandb = None

    def on_train_begin(self, logs=None):
        if self._wandb is not None:
            self._wandb.init(project=self.project, name=self.run_name)

    def on_train_batch_end(self, step, logs=None):
        rec = dict(logs or {})
        self.history.append(rec)
        if self._wandb is not None:
            self._wandb.log(rec)

    def on_train_end(self, logs=None):
        if self._wandb is not None:
            self._wandb.finish()

"""paddle.onnx — ONNX export over the static Program tape.

Reference: python/paddle/onnx/export.py (backed by paddle2onnx). This
build has no onnx/paddle2onnx dependency, so the ModelProto is emitted
directly in protobuf wire format (a ~hundred-line encoder — the format is
varint tags + length-delimited submessages) from the Program recorded by
tracing the layer. The output is a standard ONNX file loadable by any
onnxruntime.

Supported op subset covers MLP/conv classifiers (matmul/linear, elementwise
arith, activations, softmax/log_softmax, reshape/transpose/flatten, conv2d,
pooling, gather, reductions); unsupported tape ops raise with the op name.
For arbitrary programs the portable compiled artifact remains StableHLO via
paddle_tpu.jit.save(input_spec=...).
"""

from __future__ import annotations

import struct
from typing import Dict, List

import numpy as np

# ------------------------------------------------------ protobuf wire writer

def _varint(n: int) -> bytes:
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _str_field(field: int, s: str) -> bytes:
    return _len_field(field, s.encode())


# data_type codes from onnx.proto3 TensorProto.DataType
_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
          "bool": 9, "float16": 10, "float64": 11}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE[str(arr.dtype)]
    out = b"".join(_int_field(1, d) for d in arr.shape)
    out += _int_field(2, code)
    out += _str_field(8, name)
    out += _len_field(9, arr.tobytes())  # raw_data
    return out


def _value_info(name: str, shape, dtype="float32") -> bytes:
    dims = b"".join(
        _len_field(1, _int_field(1, int(d))) if int(d) >= 0
        else _len_field(1, _str_field(2, "N"))
        for d in shape)
    tensor_type = (_int_field(1, _DTYPE[dtype])
                   + _len_field(2, dims))       # shape
    type_proto = _len_field(1, tensor_type)     # tensor_type
    return _str_field(1, name) + _len_field(2, type_proto)


def _attr_int(name: str, v: int) -> bytes:
    return _str_field(1, name) + _int_field(3, v) + _int_field(20, 2)


def _attr_float(name: str, v: float) -> bytes:
    return (_str_field(1, name) + _tag(2, 5)
            + struct.pack("<f", float(v)) + _int_field(20, 1))


def _attr_ints(name: str, vs) -> bytes:
    return (_str_field(1, name)
            + b"".join(_int_field(8, int(v)) for v in vs)
            + _int_field(20, 7))


def _node(op_type: str, inputs, outputs, attrs: bytes = b"",
          name: str = "") -> bytes:
    out = b"".join(_str_field(1, i) for i in inputs)
    out += b"".join(_str_field(2, o) for o in outputs)
    if name:
        out += _str_field(3, name)
    out += _str_field(4, op_type)
    out += attrs
    return out


# -------------------------------------------------------- op tape conversion

class _Converter:
    """One Program node -> ONNX NodeProto bytes (+ extra initializers)."""

    def __init__(self):
        self.extra_inits: List[bytes] = []
        self.counter = 0

    def _const(self, arr: np.ndarray) -> str:
        name = f"const_{self.counter}"
        self.counter += 1
        self.extra_inits.append(_tensor_proto(name, arr))
        return name

    def convert(self, op_name, ins, outs, kwargs) -> List[bytes]:
        a = dict(kwargs)
        a.pop("_out_shape", None) if op_name != "flatten" else None
        simple = {
            "add": "Add", "subtract": "Sub", "multiply": "Mul",
            "divide": "Div", "pow": "Pow", "matmul": "MatMul",
            "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
            "neg": "Neg", "erf": "Erf", "floor": "Floor", "ceil": "Ceil",
            "maximum": "Max", "minimum": "Min", "where": "Where",
            "equal": "Equal", "greater_than": "Greater",
            "less_than": "Less",
        }
        if op_name in simple and not a:
            return [_node(simple[op_name], ins, outs)]
        if op_name == "linear":
            # x @ w (+ b) -> MatMul + Add (rank-general, unlike Gemm)
            if len(ins) == 3:
                mid = outs[0] + "_mm"
                return [_node("MatMul", ins[:2], [mid]),
                        _node("Add", [mid, ins[2]], outs)]
            return [_node("MatMul", ins, outs)]
        if op_name == "matmul":
            # transpose flags lower to explicit Transpose nodes
            nodes = []
            x, y = ins
            if a.get("transpose_x"):
                x2 = outs[0] + "_xT"
                nodes.append(_node("Transpose", [x], [x2]))
                x = x2
            if a.get("transpose_y"):
                y2 = outs[0] + "_yT"
                nodes.append(_node("Transpose", [y], [y2]))
                y = y2
            nodes.append(_node("MatMul", [x, y], outs))
            return nodes
        if op_name in ("softmax", "log_softmax"):
            op = "Softmax" if op_name == "softmax" else "LogSoftmax"
            return [_node(op, ins, outs,
                          _len_field(5, _attr_int("axis",
                                                  a.get("axis", -1))))]
        if op_name == "reshape":
            shape = np.asarray(a.get("shape"), np.int64)
            return [_node("Reshape",
                          [ins[0], self._const(shape)], outs)]
        if op_name == "flatten":
            start = a.get("start_axis", 0)
            stop = a.get("stop_axis", -1)
            if start == 1 and stop in (-1, None):
                # batch-dynamic safe 2-D flatten
                return [_node("Flatten", ins, outs,
                              _len_field(5, _attr_int("axis", 1)))]
            # general (start, stop): Reshape to the recorded output shape
            out_shape = a.get("_out_shape")
            if out_shape is None:
                raise NotImplementedError(
                    "flatten export: unknown output shape")
            return [_node("Reshape", [ins[0], self._const(
                np.asarray(out_shape, np.int64))], outs)]
        if op_name == "transpose":
            return [_node("Transpose", ins, outs,
                          _len_field(5, _attr_ints("perm", a["perm"])))]
        if op_name == "gelu":
            # opset-compatible Erf decomposition:
            # 0.5 x (1 + erf(x / sqrt(2)))
            x = ins[0]
            s = self._const(np.asarray(1.4142135, np.float32))
            h = self._const(np.asarray(0.5, np.float32))
            one = self._const(np.asarray(1.0, np.float32))
            n = outs[0]
            return [
                _node("Div", [x, s], [n + "_d"]),
                _node("Erf", [n + "_d"], [n + "_e"]),
                _node("Add", [n + "_e", one], [n + "_1"]),
                _node("Mul", [x, n + "_1"], [n + "_m"]),
                _node("Mul", [n + "_m", h], outs),
            ]
        if op_name == "conv2d":
            attrs = b""
            st = a.get("stride", 1)
            st = st if isinstance(st, (list, tuple)) else (st, st)
            pd = a.get("padding", 0)
            pd = pd if isinstance(pd, (list, tuple)) else (pd, pd)
            dl = a.get("dilation", 1)
            dl = dl if isinstance(dl, (list, tuple)) else (dl, dl)
            attrs += _len_field(5, _attr_ints("strides", st))
            attrs += _len_field(5, _attr_ints(
                "pads", (pd[0], pd[1], pd[0], pd[1])))
            attrs += _len_field(5, _attr_ints("dilations", dl))
            attrs += _len_field(5, _attr_int("group", a.get("groups", 1)))
            return [_node("Conv", ins, outs, attrs)]
        if op_name in ("max_pool2d", "avg_pool2d"):
            op = "MaxPool" if op_name == "max_pool2d" else "AveragePool"
            k = a.get("kernel_size")
            k = k if isinstance(k, (list, tuple)) else (k, k)
            st = a.get("stride") or k
            st = st if isinstance(st, (list, tuple)) else (st, st)
            pd = a.get("padding", 0)
            pd = pd if isinstance(pd, (list, tuple)) else (pd, pd)
            attrs = (_len_field(5, _attr_ints("kernel_shape", k))
                     + _len_field(5, _attr_ints("strides", st))
                     + _len_field(5, _attr_ints(
                         "pads", (pd[0], pd[1], pd[0], pd[1]))))
            return [_node(op, ins, outs, attrs)]
        if op_name in ("embedding", "gather", "take_along_axis"):
            if op_name == "embedding":  # (ids, weight) -> Gather(w, ids)
                return [_node("Gather", [ins[1], ins[0]], outs)]
            return [_node("Gather", ins, outs,
                          _len_field(5, _attr_int("axis",
                                                  a.get("axis", 0))))]
        if op_name in ("mean", "sum", "max", "min"):
            op = {"mean": "ReduceMean", "sum": "ReduceSum",
                  "max": "ReduceMax", "min": "ReduceMin"}[op_name]
            attrs = _len_field(5, _attr_int(
                "keepdims", 1 if a.get("keepdim") else 0))
            ax = a.get("axis")
            if ax is not None:
                ax = ax if isinstance(ax, (list, tuple)) else (ax,)
                if op == "ReduceSum":
                    # axes is an INPUT from opset 13 (attribute rejected)
                    return [_node(op, list(ins) + [self._const(
                        np.asarray(ax, np.int64))], outs, attrs)]
                attrs += _len_field(5, _attr_ints("axes", ax))
            return [_node(op, ins, outs, attrs)]
        if op_name == "cast":
            return [_node("Cast", ins, outs,
                          _len_field(5, _attr_int(
                              "to", _DTYPE[str(a.get("dtype"))])))]
        if op_name == "scale":
            s = self._const(np.asarray(a.get("scale", 1.0), np.float32))
            b = a.get("bias", 0.0)
            if b:
                mid = outs[0] + "_s"
                return [_node("Mul", [ins[0], s], [mid]),
                        _node("Add", [mid, self._const(
                            np.asarray(b, np.float32))], outs)]
            return [_node("Mul", [ins[0], s], outs)]
        raise NotImplementedError(
            f"paddle.onnx.export: op '{op_name}' has no ONNX mapping yet "
            "(supported: arith/activations/matmul/conv2d/pool/softmax/"
            "reshape/transpose/gather/reductions). For arbitrary programs "
            "use paddle_tpu.jit.save(input_spec=...) -> StableHLO.")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace `layer` into a static Program and write `path`(.onnx).

    input_spec: list of static.InputSpec (shape may contain -1/None for a
    dynamic batch dim)."""
    import jax

    from paddle_tpu import static
    from paddle_tpu.core.dtype import to_jax_dtype
    from paddle_tpu.ops.registry import _Slot

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")
    if not path.endswith(".onnx"):
        path = path + ".onnx"

    prog = static.Program()
    with static.program_guard(prog):
        feeds = []
        for i, spec in enumerate(input_spec):
            shape = [1 if s in (-1, None) else int(s) for s in spec.shape]
            name = getattr(spec, "name", None) or f"x{i}"
            feeds.append(static.data(name, shape,
                                     dtype=getattr(spec, "dtype",
                                                   "float32")))
        outs = layer(*feeds)
    out_list = outs if isinstance(outs, (tuple, list)) else [outs]

    # value id -> ONNX name
    names: Dict[int, str] = {}
    for t, spec, i in zip(feeds, input_spec, range(len(feeds))):
        names[t._value.vid] = getattr(spec, "name", None) or f"x{i}"

    initializers = []
    for vid, const in prog.constants.items():
        nm = f"p_{vid}"
        names[vid] = nm
        initializers.append(_tensor_proto(nm, np.asarray(const)))

    import inspect

    from paddle_tpu.ops.registry import OPS

    conv = _Converter()
    nodes = []
    for n in prog.nodes:
        for vid in n.input_ids:
            names.setdefault(vid, f"v_{vid}")
        for vid in n.out_ids:
            names.setdefault(vid, f"v_{vid}")
        ins = [names[v] for v in n.input_ids]
        kw = {}
        # positional non-tensor attrs map to parameter names via the
        # impl's signature (the tape stores them inline in args_tpl)
        impl = n.impl or (OPS[n.op_name].impl if n.op_name in OPS else None)
        if impl is not None:
            try:
                pnames = list(inspect.signature(impl).parameters)
            except (TypeError, ValueError):
                pnames = []
            for i, a in enumerate(n.args_tpl):
                if not isinstance(a, _Slot) and i < len(pnames) \
                        and a is not None:
                    kw[pnames[i]] = a
        for k, v in n.kwargs_tpl:
            if not isinstance(v, _Slot):
                kw[k] = v
        kw["_out_shape"] = tuple(prog.avals[n.out_ids[0]].shape)
        nodes.extend(conv.convert(n.op_name, ins,
                                  [names[v] for v in n.out_ids], kw))

    g = b"".join(_len_field(1, nd) for nd in nodes)
    g += _str_field(2, "paddle_tpu")
    g += b"".join(_len_field(5, t)
                  for t in initializers + conv.extra_inits)
    for t, spec, i in zip(feeds, input_spec, range(len(feeds))):
        shape = [(-1 if s in (-1, None) else int(s)) for s in spec.shape]
        g += _len_field(11, _value_info(
            names[t._value.vid], shape,
            str(getattr(spec, "dtype", "float32"))))
    for t in out_list:
        sym = t._value
        g += _len_field(12, _value_info(
            names.get(sym.vid, f"v_{sym.vid}"), sym.aval.shape,
            str(sym.aval.dtype)))

    model = _int_field(1, 8)                        # ir_version
    model += _str_field(2, "paddle_tpu")            # producer
    model += _len_field(7, g)                       # graph
    model += _len_field(8, _int_field(2, opset_version))  # opset_import
    with open(path, "wb") as f:
        f.write(model)
    return path

"""paddle.onnx — export stub.

Reference: paddle.onnx.export (python/paddle/onnx/export.py, backed by the
external paddle2onnx package). In this stack the portable compiled artifact
is StableHLO (paddle.jit.save with input_spec) — the XLA-world equivalent of
an ONNX export; a true ONNX emitter would need an ONNX runtime/converter
dependency this environment doesn't ship.
"""

from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not available (no paddle2onnx/onnx dependency in "
        "this build). Use paddle_tpu.jit.save(layer, path, input_spec=...) "
        "to produce a portable serialized StableHLO module instead."
    )

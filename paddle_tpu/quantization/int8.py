"""Int8 execution path: weight-only + LLM.int8 linears, QDQ ops.

Reference surface: phi kernels weight_quantize / weight_dequantize /
weight_only_linear (paddle/phi/kernels/gpu/weight_only_linear_kernel.cu),
llm_int8_linear, quantize_linear / dequantize_linear (QDQ, fake_quantize
family in paddle/phi/kernels/fake_quantize_*), apply_per_channel_scale.

TPU-native: the MXU multiplies int8 at 2x bf16 throughput (v5e: 394 vs
197 TOPS), so real int8 execution is lax.dot_general with
preferred_element_type=int32 over per-channel/per-token scales — no
custom kernels needed; XLA fuses the (de)quantize elementwise chains.
Weight-only mode keeps int8 weights in HBM (halving weight bandwidth)
and dequantizes inside the fused matmul epilogue.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import OPS, OpDef, dispatch


def _u(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(x):
    return Tensor._wrap(jnp.asarray(x))


def _as_t(v):
    return v if isinstance(v, Tensor) else _wrap(v)


# ------------------------------------------------------------ weight quant

def _pack_int4(q):
    """Pack two signed int4 rows per int8 byte along axis 0 (the in-channel
    axis), matching the reference weight-only int4 storage density
    (weight_quantize_kernel.cu packs pairs; we use low-nibble = even row,
    high-nibble = odd row as our documented layout)."""
    k = q.shape[0]
    if k % 2:
        raise ValueError(f"int4 packing needs an even in-dim, got {k}")
    lo = q[0::2].astype(jnp.int32) & 0xF
    hi = q[1::2].astype(jnp.int32) & 0xF
    return (lo | (hi << 4)).astype(jnp.int8)


def _unpack_int4(packed):
    """Inverse of _pack_int4: int8 [k//2, n] -> signed int4 values
    [k, n] (still int8 dtype)."""
    p = packed.astype(jnp.int32) & 0xFF
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    lo = lo - 16 * (lo >= 8)   # sign-extend 4-bit two's complement
    hi = hi - 16 * (hi >= 8)
    k2, n = packed.shape
    out = jnp.stack([lo, hi], axis=1).reshape(2 * k2, n)
    return out.astype(jnp.int8)


def _weight_quantize(w, algo="weight_only_int8", group_size=-1):
    """Per-output-channel symmetric abs-max quant. w: [in, out] ->
    (qw int8, scale fp [out]). int4 packs two values per byte along the
    in-dim, so qw is [in//2, out] for int4 (not interchangeable with
    reference CUDA tile-permuted layouts, but the same density; layout is
    documented on _pack_int4).

    STRICTLY 2-D: the per-channel scale is computed over axis 0 (the
    in-dim). A fused-QKV weight stored (3, num_heads, head_dim) — the
    layout GPT's attention block reshapes into — would silently get its
    scales computed over the q/k/v axis instead of the in-dim, so
    non-2-D inputs are a loud error rather than a wrong answer: reshape
    to [in, 3 * num_heads * head_dim] first (per fused output column,
    which is what the serving runner quantizes)."""
    if w.ndim != 2:
        raise ValueError(
            f"weight_quantize needs a 2-D [in, out] matrix, got shape "
            f"{tuple(w.shape)}: per-output-channel scales reduce over "
            "axis 0 (the in-dim). A fused-QKV weight in the (3, "
            "num_heads, head_dim) layout must be reshaped/flattened to "
            "[in, 3*num_heads*head_dim] before quantizing — quantizing "
            "the raw 3-D layout would silently compute scales over the "
            "qkv axis and mis-scale every channel")
    bits = 4 if "int4" in algo else 8
    qmax = 2.0 ** (bits - 1) - 1
    if group_size and group_size > 0:
        k, n = w.shape
        g = k // group_size
        wg = w.reshape(g, group_size, n)
        scale = jnp.abs(wg).max(axis=1) / qmax          # [g, n]
        q = jnp.clip(jnp.round(wg / jnp.maximum(scale, 1e-9)[:, None, :]),
                     -qmax, qmax).reshape(k, n).astype(jnp.int8)
        if bits == 4:
            q = _pack_int4(q)
        return q, scale
    scale = jnp.abs(w).max(axis=0) / qmax               # [out]
    # zero channels (pruned / zero-init) quantize to 0, not NaN
    q = jnp.clip(jnp.round(w / jnp.maximum(scale, 1e-9)[None, :]),
                 -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        q = _pack_int4(q)
    return q, scale


def _weight_dequantize(qw, scale, algo="weight_only_int8", group_size=-1):
    if "int4" in algo:
        qw = _unpack_int4(qw)
    if scale.ndim == 2:  # grouped
        k, n = qw.shape
        g = scale.shape[0]
        return (qw.reshape(g, k // g, n).astype(scale.dtype)
                * scale[:, None, :]).reshape(k, n)
    return qw.astype(scale.dtype) * scale[None, :]


OPS.setdefault("weight_quantize", OpDef("weight_quantize", _weight_quantize,
                                        diff=False, method=False))
OPS.setdefault("weight_dequantize",
               OpDef("weight_dequantize", _weight_dequantize, diff=False,
                     method=False))


def weight_quantize(x, algo="weight_only_int8", group_size=-1):
    q, s = dispatch("weight_quantize", (_as_t(x),),
                    {"algo": algo, "group_size": group_size})
    return q, s


def weight_dequantize(x, scale, algo="weight_only_int8", group_size=-1):
    return dispatch("weight_dequantize", (_as_t(x), _as_t(scale)),
                    {"algo": algo, "group_size": group_size})


def _weight_only_linear(x, qw, weight_scale, bias=None,
                        weight_dtype="int8", group_size=-1):
    """fp activation x int8 weight: dequant rides the matmul epilogue
    (XLA fuses scale-multiply into the dot consumer)."""
    w = _weight_dequantize(qw, weight_scale.astype(x.dtype),
                           algo=f"weight_only_{weight_dtype}",
                           group_size=group_size)
    out = x @ w
    if bias is not None:
        out = out + bias
    return out


OPS.setdefault("weight_only_linear",
               OpDef("weight_only_linear", _weight_only_linear, diff=True,
                     method=False))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    return dispatch("weight_only_linear",
                    (_as_t(x), _as_t(weight), _as_t(weight_scale),
                     _as_t(bias) if bias is not None else None),
                    {"weight_dtype": weight_dtype, "group_size": group_size})


# ------------------------------------------------------------ llm.int8

def _llm_int8_linear(x, qw, weight_scale, bias=None, threshold=6.0):
    """LLM.int8 [Dettmers 2022]: outlier activation columns run in fp,
    the rest as int8 x int8 -> int32 on the MXU with per-token dynamic
    activation scales."""
    qmax = 127.0
    absx = jnp.abs(x)
    outlier = (absx.max(axis=tuple(range(x.ndim - 1))) >= threshold)  # [in]
    x_reg = jnp.where(outlier[None, :], 0.0, x.reshape(-1, x.shape[-1]))
    # per-token dynamic abs-max quant of the regular columns
    xs = jnp.maximum(jnp.abs(x_reg).max(axis=-1, keepdims=True), 1e-8) / qmax
    xq = jnp.clip(jnp.round(x_reg / xs), -qmax, qmax).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, qw, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)               # [tokens, out] int32
    reg = acc.astype(x.dtype) * xs * weight_scale[None, :].astype(x.dtype)
    # outlier columns at full precision against dequantized weight rows
    w_out = (qw.astype(x.dtype) * weight_scale[None, :]) * \
        outlier[:, None].astype(x.dtype)
    x_out = x.reshape(-1, x.shape[-1]) * outlier[None, :].astype(x.dtype)
    out = (reg + x_out @ w_out).reshape(*x.shape[:-1], qw.shape[1])
    if bias is not None:
        out = out + bias
    return out


OPS.setdefault("llm_int8_linear", OpDef("llm_int8_linear", _llm_int8_linear,
                                        diff=False, method=False))


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    return dispatch("llm_int8_linear",
                    (_as_t(x), _as_t(weight), _as_t(weight_scale),
                     _as_t(bias) if bias is not None else None),
                    {"threshold": threshold})


def _apply_per_channel_scale(x, scales):
    return x * scales


OPS.setdefault("apply_per_channel_scale",
               OpDef("apply_per_channel_scale", _apply_per_channel_scale,
                     diff=True, method=False))


def apply_per_channel_scale(x, scales):
    """Pre-scale activations per channel before a weight-only matmul
    (smooth-quant style; reference apply_per_channel_scale op)."""
    return dispatch("apply_per_channel_scale", (_as_t(x), _as_t(scales)), {})


# ------------------------------------------------------------ QDQ ops

def _quantize_linear(x, scale, zero_point=None, axis=-1, bit_length=8,
                     round_type=0):
    qmax = 2.0 ** (bit_length - 1) - 1
    if scale.ndim == 0 or scale.size == 1:
        s = scale.reshape(())
    else:  # per-channel along `axis`
        shape = [1] * x.ndim
        shape[axis] = -1
        s = scale.reshape(shape)
    q = jnp.clip(jnp.round(x / jnp.maximum(s, 1e-9) * qmax), -qmax, qmax)
    return q.astype(jnp.int8)


def _dequantize_linear(x, scale, zero_point=None, axis=-1, bit_length=8):
    qmax = 2.0 ** (bit_length - 1) - 1
    if scale.ndim == 0 or scale.size == 1:
        s = scale.reshape(())
    else:
        shape = [1] * x.ndim
        shape[axis] = -1
        s = scale.reshape(shape)
    return x.astype(scale.dtype) * s / qmax


OPS.setdefault("quantize_linear", OpDef("quantize_linear", _quantize_linear,
                                        diff=False, method=False))
OPS.setdefault("dequantize_linear",
               OpDef("dequantize_linear", _dequantize_linear, diff=False,
                     method=False))


def quantize_linear(x, scale, zero_point=None, axis=-1, bit_length=8):
    return dispatch("quantize_linear", (_as_t(x), _as_t(scale)),
                    {"axis": axis, "bit_length": bit_length})


def dequantize_linear(x, scale, zero_point=None, axis=-1, bit_length=8):
    return dispatch("dequantize_linear", (_as_t(x), _as_t(scale)),
                    {"axis": axis, "bit_length": bit_length})


# ----------------------------------------------- fake_quantize family

def _fq_abs_max(x, bit_length=8):
    qmax = 2.0 ** (bit_length - 1) - 1
    scale = jnp.abs(x).max()
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-9) * qmax), -qmax, qmax)
    return q, scale


def _fq_channel_wise_abs_max(x, bit_length=8, quant_axis=0):
    qmax = 2.0 ** (bit_length - 1) - 1
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.abs(x).max(axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    q = jnp.clip(jnp.round(x / jnp.maximum(scale.reshape(shape), 1e-9)
                           * qmax), -qmax, qmax)
    return q, scale


def _fq_dequant_abs_max(x, bit_length=8):
    q, scale = _fq_abs_max(x, bit_length)
    qmax = 2.0 ** (bit_length - 1) - 1
    dq = q * scale / qmax
    return x + jax.lax.stop_gradient(dq - x), scale  # STE


def _fake_dequantize_max_abs(x, scale, max_range):
    return x.astype(scale.dtype) * scale / max_range


def _dequantize_log(x, dict_table):
    """Log-quantized lookup dequant (reference dequantize_log_op): int8
    code -> table[|code|] with sign."""
    idx = jnp.abs(x.astype(jnp.int32))
    val = jnp.take(dict_table, idx)
    return jnp.where(x < 0, -val, val)


for _n, _f, _d in (
        ("fake_quantize_abs_max", _fq_abs_max, False),
        ("fake_channel_wise_quantize_abs_max", _fq_channel_wise_abs_max,
         False),
        ("fake_quantize_dequantize_abs_max", _fq_dequant_abs_max, True),
        ("fake_dequantize_max_abs", _fake_dequantize_max_abs, False),
        ("dequantize_abs_max", _fake_dequantize_max_abs, False),
        ("dequantize_log", _dequantize_log, False)):
    OPS.setdefault(_n, OpDef(_n, _f, diff=_d, method=False))

# Moving-average / range / channel-wise variants get dedicated functional
# impls matching the reference op semantics (fake_quantize_op.cc): the
# stateful scale trackers become explicit (state in, state out) so the op
# is jit-pure; the layer wrappers in quantization/__init__.py own the
# buffers. (Round-2 advisor finding: these were aliased to the per-tensor
# QDQ helper, which silently computed the wrong thing.)

def _fq_moving_average_abs_max(x, in_scale, in_accum=None, in_state=None,
                               moving_rate=0.9, bit_length=8, is_test=False):
    """Quant-only output + EMA scale state. Ref
    FakeQuantizeMovingAverageAbsMaxOp: accum = r*accum + max|x|,
    state = r*state + 1, scale = accum/state."""
    qmax = 2.0 ** (bit_length - 1) - 1
    if is_test or in_accum is None:
        scale = in_scale.reshape(())
        q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-9) * qmax),
                     -qmax, qmax)
        return q, scale
    cur = jnp.abs(x).max()
    accum = moving_rate * in_accum.reshape(()) + cur
    state = moving_rate * in_state.reshape(()) + 1.0
    scale = accum / state
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-9) * qmax), -qmax, qmax)
    return q, scale, state, accum


def _fq_dq_moving_average_abs_max(x, in_scale, in_accum=None, in_state=None,
                                  moving_rate=0.9, bit_length=8,
                                  is_test=False):
    """QDQ (straight-through) variant of the moving-average quantizer."""
    res = _fq_moving_average_abs_max(x, in_scale, in_accum, in_state,
                                     moving_rate, bit_length, is_test)
    q, scale, rest = res[0], res[1], res[2:]
    qmax = 2.0 ** (bit_length - 1) - 1
    dq = q * scale / qmax
    out = x + jax.lax.stop_gradient(dq - x)
    return (out, scale) + tuple(rest)


def _fq_range_abs_max(x, in_scale, iter_=0, window_size=10000, bit_length=8,
                      is_test=False):
    """Windowed-range quantizer (ref FakeQuantizeRangeAbsMaxOp): scale
    resets to max|x| at each window boundary, else grows monotonically."""
    qmax = 2.0 ** (bit_length - 1) - 1
    if is_test:
        scale = in_scale.reshape(())
    else:
        cur = jnp.abs(x).max()
        at_window_start = (jnp.asarray(iter_) % window_size) == 0
        scale = jnp.where(at_window_start, cur,
                          jnp.maximum(in_scale.reshape(()), cur))
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-9) * qmax), -qmax, qmax)
    return q, scale


def _fq_dq_channel_wise_abs_max(x, bit_length=8, quant_axis=0):
    """Per-channel QDQ with straight-through gradient."""
    q, scale = _fq_channel_wise_abs_max(x, bit_length, quant_axis)
    qmax = 2.0 ** (bit_length - 1) - 1
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    dq = q * scale.reshape(shape) / qmax
    return x + jax.lax.stop_gradient(dq - x), scale


def _fake_channel_wise_dequantize_max_abs(x, scale, quant_bits=8,
                                          quant_axis=0):
    """Per-channel dequantize: x * scale / (2^(bits-1)-1) broadcast along
    quant_axis (ref FakeChannelWiseDequantizeMaxAbsOp, single-scale form)."""
    max_range = 2.0 ** (quant_bits - 1) - 1
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    return x.astype(scale.dtype) * scale.reshape(shape) / max_range


for _n, _f, _d in (
        ("fake_quantize_moving_average_abs_max",
         _fq_moving_average_abs_max, False),
        ("fake_quantize_dequantize_moving_average_abs_max",
         _fq_dq_moving_average_abs_max, True),
        ("fake_quantize_range_abs_max", _fq_range_abs_max, False),
        ("fake_channel_wise_quantize_dequantize_abs_max",
         _fq_dq_channel_wise_abs_max, True),
        ("fake_channel_wise_dequantize_max_abs",
         _fake_channel_wise_dequantize_max_abs, False)):
    OPS.setdefault(_n, OpDef(_n, _f, diff=_d, method=False))


# ------------------------------------------------------------ int8 layer

from paddle_tpu.nn.layer import Layer  # noqa: E402


class Int8Linear(Layer):
    """Real int8 execution Linear for converted models: int8 weights in
    HBM, per-token dynamic activation quant, int8 x int8 -> int32 MXU
    matmul (the deployment target of QAT/PTQ convert(to_int8=True))."""

    def __init__(self, linear):
        super().__init__()
        w = _u(linear.weight)
        qw, scale = _weight_quantize(w)
        self.register_buffer("qweight", _wrap(qw))
        self.register_buffer("scale", _wrap(scale))
        self.bias = linear.bias

    def forward(self, x):
        xv = _u(x)
        qmax = 127.0
        flat = xv.reshape(-1, xv.shape[-1])
        xs = jnp.maximum(jnp.abs(flat).max(axis=-1, keepdims=True),
                         1e-8) / qmax
        xq = jnp.clip(jnp.round(flat / xs), -qmax, qmax).astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, _u(self.qweight), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(xv.dtype) * xs * _u(self.scale)[None, :].astype(
            xv.dtype)
        out = out.reshape(*xv.shape[:-1], out.shape[-1])
        if self.bias is not None:
            out = out + _u(self.bias)
        return _wrap(out)

"""Quantization: QAT fake-quant + PTQ observers.

Reference: python/paddle/quantization/ (QuantConfig, QAT quanter insertion,
PTQ observers) + fake_quantize ops (phi/kernels/fake_quantize_*).

TPU-native: int8 is MXU-native on TPU; fake-quant in training simulates it,
and the convert step materializes int8 weights + scales. Per-tensor abs-max
quantization (the reference default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer import Layer
from paddle_tpu.ops.registry import OPS, OpDef, dispatch


def _fake_quant(x, scale, bit_length=8):
    """Simulated quantization with straight-through estimator."""
    qmax = 2.0 ** (bit_length - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    dq = q * s / qmax
    # STE: forward uses dq, backward passes through
    return x + jax.lax.stop_gradient(dq - x)


OPS.setdefault("fake_quantize_dequantize",
               OpDef("fake_quantize_dequantize", _fake_quant, diff=True,
                     method=False))


def fake_quantize_dequantize(x, scale, bit_length=8):
    return dispatch("fake_quantize_dequantize", (x, scale),
                    {"bit_length": bit_length})


class AbsmaxObserver:
    """PTQ observer collecting per-tensor abs-max (reference
    quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._max = 0.0

    def observe(self, x: Tensor):
        self._max = max(self._max, float(jnp.abs(x._value).max()))

    def scale(self) -> float:
        return self._max or 1.0


class FakeQuanterWithAbsMax(Layer):
    """QAT quanter: tracks a running abs-max and fake-quantizes
    (reference quanters/abs_max.py)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        # scale < 0 marks "not yet observed": first batch sets it directly
        self.register_buffer("scale",
                             Tensor._wrap(-jnp.ones((), jnp.float32)))

    def forward(self, x):
        if self.training:
            cur = jnp.abs(x._value).max().astype(jnp.float32)
            prev = self.scale._value
            new = jnp.where(prev < 0, cur,
                            self.moving_rate * prev
                            + (1 - self.moving_rate) * cur)
            self.scale._value = new
        # unobserved (eval before any training batch): calibrate on the fly
        safe = jnp.where(self.scale._value < 0,
                         jnp.abs(jnp.asarray(x._value)).max(),
                         self.scale._value)
        return fake_quantize_dequantize(x, Tensor._wrap(safe),
                                        bit_length=self.quant_bits)


class QuantedLinear(Layer):
    """Linear with fake-quantized weights + activations (QAT)."""

    def __init__(self, linear, q_config=None):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.activation_quanter = FakeQuanterWithAbsMax()
        self.weight_quanter = FakeQuanterWithAbsMax()

    def forward(self, x):
        xq = self.activation_quanter(x)
        wq = self.weight_quanter(self.weight)
        return F.linear(xq, wq, self.bias)


class QuantConfig:
    """Reference: quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_types = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_types[layer_type] = (activation, weight)


class QAT:
    """Quantization-aware training: swap Linear -> QuantedLinear
    (reference quantization/qat.py)."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace=False):
        from paddle_tpu.nn.layers import Linear

        for name, sub in list(model.named_sublayers(include_self=True)):
            for child_name, child in list(sub._sub_layers.items()):
                if isinstance(child, Linear):
                    sub._sub_layers[child_name] = QuantedLinear(child)
        return model

    def convert(self, model: Layer, inplace=False, to_int8=False):
        """Materialize int8 weights + scales for deployment. With
        `to_int8=True`, swap each QuantedLinear for an Int8Linear that
        EXECUTES int8 x int8 on the MXU (quantization/int8.py) instead of
        keeping the QDQ simulation."""
        from paddle_tpu.quantization.int8 import Int8Linear, weight_quantize

        def materialize(child):
            qw, s = weight_quantize(child.weight)
            child._int8_weight = np.asarray(qw._value)
            child._weight_scale = np.asarray(s._value)

        if isinstance(model, QuantedLinear):  # root layer itself
            if to_int8:
                return Int8Linear(model)
            materialize(model)
            return model
        for _, sub in model.named_sublayers(include_self=True):
            for child_name, child in list(sub._sub_layers.items()):
                if not isinstance(child, QuantedLinear):
                    continue
                if to_int8:
                    sub._sub_layers[child_name] = Int8Linear(child)
                else:
                    materialize(child)
        return model


class PTQ:
    """Post-training quantization: run calibration batches through observers
    (reference quantization/ptq.py)."""

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig()
        self._observers = {}

    def quantize(self, model: Layer, inplace=False):
        from paddle_tpu.nn.layers import Linear

        for name, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, Linear):
                obs = AbsmaxObserver()
                self._observers[name] = obs

                def make_hook(o):
                    def hook(layer, inputs):
                        o.observe(inputs[0])

                    return hook

                sub.register_forward_pre_hook(make_hook(obs))
        return model

    def convert(self, model: Layer, inplace=False):
        """Bake observed scales into fake-quant wrappers."""
        from paddle_tpu.nn.layers import Linear

        for name, sub in model.named_sublayers(include_self=True):
            for child_name, child in list(sub._sub_layers.items()):
                full = (name + "." if name else "") + child_name
                if isinstance(child, Linear) and full in self._observers:
                    q = QuantedLinear(child)
                    q.activation_quanter.scale._value = jnp.asarray(
                        self._observers[full].scale(), jnp.float32)
                    q.eval()
                    sub._sub_layers[child_name] = q
        return model


from paddle_tpu.quantization.int8 import (  # noqa: F401,E402
    Int8Linear, apply_per_channel_scale, dequantize_linear, llm_int8_linear,
    quantize_linear, weight_dequantize, weight_only_linear, weight_quantize,
)
from paddle_tpu.quantization.int4 import (  # noqa: F401,E402
    int4_dequantize, int4_dequantize_reference, int4_matmul, int4_quantize,
    int4_weight_bytes,
)
from paddle_tpu.quantization.qcomm import (  # noqa: F401,E402
    allgather_bytes, allreduce_bytes, quantized_allgather,
    quantized_allgather_reference, quantized_allreduce_reference,
    quantized_psum,
)

"""Packed int4 weight-only quantization with group-wise scales (ISSUE 19).

Reference surface: the weight_only_int4 arm of phi's weight_quantize /
weight_only_linear family (weight_quantize_kernel.cu packs two 4-bit
values per byte; PaddleNLP's weight-only int4 path groups the scales
along the reduction dim). TPU-native like quantization/int8.py: no
custom kernels — the packed codes live in HBM, the unpack + dequant
rides the jitted matmul epilogue and XLA fuses the elementwise chains.

Why groups: at 4 bits a single per-output-channel scale must cover the
whole in-dim's dynamic range with 15 code levels — one outlier row
poisons every other row of that column. Group-wise scales (one fp32
scale per `group_size` reduction rows per output channel, default 128)
bound an outlier's blast radius to its own group, which is what makes
int4 usable at serving accuracy gates (top-5 >= 0.99 vs fp32).

Storage layout (the serving runner's params-dict contract):

  codes   int8 [ceil(in/2), out] — `_pack_int4`'s nibble layout
          (low nibble = even in-row, high nibble = odd in-row);
  scales  fp32 [out, n_groups],  n_groups = ceil(in / group_size) —
          TRANSPOSED vs int8.py's grouped `[g, n]` convention so the
          out-dim leads like the per-channel int8 scale vector and the
          resilience auditor can pin one shape formula per param.

`int4_matmul` is the dequant-in-epilogue contract: the matmul runs as
a grouped partial-product einsum and each group's partial output is
multiplied by its scale BEFORE the group-sum — exactly
`x @ dequantize(codes, scales)` by linearity, with only int8 codes +
fp32 scales resident. All jnp ops, jit/shard_map-pure.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from paddle_tpu.quantization.int8 import _pack_int4, _unpack_int4

# symmetric signed-int4 code range: [-7, 7] (like the int8 path we keep
# the symmetric grid and never use -8, so negation is exact)
INT4_QMAX = 7.0

# default reduction-dim group size: 128 keeps scale overhead at
# 4 bytes / (128 * 0.5 bytes) = 6.25% while bounding outlier damage
INT4_GROUP_SIZE = 128


def _check_2d(w, what: str = "int4_quantize"):
    if w.ndim != 2:
        raise ValueError(
            f"{what} needs a 2-D [in, out] matrix, got shape "
            f"{tuple(w.shape)}: group scales reduce over axis 0 (the "
            "in-dim). A fused-QKV weight in the (3, num_heads, head_dim) "
            "layout must be reshaped/flattened to [in, 3*num_heads*"
            "head_dim] first — quantizing the raw 3-D layout would "
            "silently compute scales over the qkv axis and mis-scale "
            "every channel (the ISSUE 9 loud-error rule, generalized)")


def _group_geometry(k: int, group_size: int):
    if group_size < 1:
        raise ValueError(f"group_size must be >= 1, got {group_size}")
    g = min(int(group_size), int(k))
    n_groups = -(-int(k) // g)
    return g, n_groups


def int4_quantize(w, group_size: int = INT4_GROUP_SIZE):
    """Quantize a 2-D [in, out] weight to packed int4 codes + group
    scales: returns `(codes int8 [in//2, out], scales fp32
    [out, ceil(in/group_size)])`. Symmetric abs-max per (group, output
    channel); a partial last group (in % group_size != 0) is padded
    with zeros for the abs-max, so its scale is honest for the real
    rows. The in-dim must be even (the nibble packing is loud about
    odd dims)."""
    w = jnp.asarray(w)
    _check_2d(w)
    k, n = w.shape
    g, n_groups = _group_geometry(k, group_size)
    pad = n_groups * g - k
    wf = w.astype(jnp.float32)
    if pad:
        wf = jnp.pad(wf, ((0, pad), (0, 0)))
    wg = wf.reshape(n_groups, g, n)                        # [G, g, n]
    scale = jnp.abs(wg).max(axis=1) / INT4_QMAX            # [G, n]
    # zero groups (pruned / padded) quantize to 0, not NaN
    q = jnp.clip(jnp.round(wg / jnp.maximum(scale, 1e-9)[:, None, :]),
                 -INT4_QMAX, INT4_QMAX)
    q = q.reshape(n_groups * g, n)[:k].astype(jnp.int8)
    return _pack_int4(q), scale.T.astype(jnp.float32)      # [n, G]


def int4_matmul(x, codes, scale, group_size: int = INT4_GROUP_SIZE):
    """`x @ dequantize(codes, scale)` with the dequant in the epilogue:
    unpack the nibbles, run the matmul as per-group partial products,
    multiply each group's partial output by its scale, THEN sum the
    groups — the packed codes are the only weight-sized HBM residents
    and XLA fuses the unpack/scale chains into the dot consumers.
    `x`: [..., in] any float dtype; returns [..., out] at x's dtype."""
    q = _unpack_int4(codes)                                # [k, n] int8
    k, n = q.shape
    g, n_groups = _group_geometry(k, group_size)
    lead = x.shape[:-1]
    xr = x.reshape(-1, k)
    pad = n_groups * g - k
    if pad:
        xr = jnp.pad(xr, ((0, 0), (0, pad)))
        q = jnp.pad(q, ((0, pad), (0, 0)))
    xg = xr.reshape(xr.shape[0], n_groups, g)              # [R, G, g]
    wg = q.reshape(n_groups, g, n).astype(x.dtype)         # [G, g, n]
    part = jnp.einsum("rgi,gin->rgn", xg, wg)              # [R, G, n]
    out = (part * scale.T[None].astype(x.dtype)).sum(axis=1)
    return out.reshape(*lead, n)


def int4_dequantize(codes, scale, group_size: int = INT4_GROUP_SIZE):
    """Expand packed codes + group scales back to the fp32 [in, out]
    weight (tests / debugging — the serving path never materializes
    this; it feeds `int4_matmul` instead)."""
    q = _unpack_int4(codes)
    k, n = q.shape
    g, n_groups = _group_geometry(k, group_size)
    pad = n_groups * g - k
    if pad:
        q = jnp.pad(q, ((0, pad), (0, 0)))
    wg = q.reshape(n_groups, g, n).astype(jnp.float32)
    return (wg * scale.T[:, None, :]).reshape(n_groups * g, n)[:k]


def int4_dequantize_reference(codes, scale,
                              group_size: int = INT4_GROUP_SIZE):
    """Pure-numpy oracle of `int4_dequantize` — the unit tests compare
    the jitted epilogue against `x @ this` to fp32 matmul tolerance."""
    p = np.asarray(codes).astype(np.int32) & 0xFF
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    lo = lo - 16 * (lo >= 8)
    hi = hi - 16 * (hi >= 8)
    k2, n = p.shape
    q = np.stack([lo, hi], axis=1).reshape(2 * k2, n)
    k = q.shape[0]
    s = np.asarray(scale, np.float32)
    g, n_groups = _group_geometry(k, group_size)
    pad = n_groups * g - k
    if pad:
        q = np.pad(q, ((0, pad), (0, 0)))
    wg = q.reshape(n_groups, g, n).astype(np.float32)
    return (wg * s.T[:, None, :]).reshape(n_groups * g, n)[:k]


def int4_weight_bytes(k: int, n: int,
                      group_size: int = INT4_GROUP_SIZE) -> int:
    """Resident HBM bytes of one quantized [k, n] weight — packed code
    bytes PLUS group-scale bytes, the honest accounting the serving
    `weight_bytes()` counters commit (never an assumed 8x)."""
    g, n_groups = _group_geometry(int(k), group_size)
    return (int(k) // 2) * int(n) + int(n) * n_groups * 4

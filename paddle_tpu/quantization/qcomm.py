"""Quantized collectives for tensor-parallel serving (ISSUE 15).

Reference: EQuARX (PAPERS.md) — an in-XLA quantized allreduce recovers
most of the row-parallel psum's interconnect bandwidth at negligible
quality cost. TP serving moves fp32 activations through the
row-parallel allreduce on every o_proj/down_proj of every layer; after
PRs 9-14 quantized the KV pools, the weights, and the handoff paths,
that psum is the last fp32-width hot path left.

`quantized_psum` is the reusable primitive: a CHUNKED TWO-LEVEL reduce
that replaces one fp32 `lax.psum` inside a shard_map body.

  level 1 (scales)  each shard computes a per-(row, chunk) abs-max
                    scale over its own partial sums, then the shards
                    agree on ONE shared scale per chunk via
                    `lax.pmax` — a tiny fp32 collective. Sharing by
                    max keeps the scales per-shard-honest: every
                    shard's values fit the shared scale, so the int8
                    quantization below can never clip (the clip is a
                    guard, not a rounding path).
  level 2 (codes)   each shard quantizes its partial sums at the
                    shared scale and the int8 codes allreduce
                    (accumulated wide — int32 — transmitted narrow;
                    a real ring implementation requantizes per hop,
                    which is what the byte accounting models), then
                    one dequant multiply recovers the sum.

Chunking is along the LAST axis of each row, never across rows: a
row's quantization depends only on that row's values, so the reduced
output is BATCH-SHAPE INVARIANT — the same token position produces
bit-identical values whether it rides a monolithic prefill, a chunked
prefill, a mixed ragged batch, or a decode horizon (padding rows and
dead slots cannot leak into live rows). That invariance is what lets
the serving engine stay token-exact against its own naive oracle with
the quantized psum on; accuracy vs the FP32 engine is gated instead
(teacher-forced |dlogit| / top-5 overlap / greedy agreement, the PR 9
methodology).

`allreduce_bytes` is the honest wire accounting the serving counters
use: per shard, the fp32 psum moves rows*width*4 bytes; the quantized
one moves rows*width int8 code bytes PLUS 4 bytes per (row, chunk)
shared scale — scale bytes are counted, so the committed reduction is
4 / (1 + 4/chunk), measured, never an assumed 4x.

ISSUE 19 adds the OTHER direction: `quantized_allgather` quantizes
the column-parallel all-gather (the lm_head's logits gather) with the
same pmax-shared per-(row, chunk) scales — codes gathered wide, one
dequant — and `allgather_bytes` its honest per-shard wire accounting.

Everything here is jit-pure and shard_map-compatible: no host state,
no python branches on traced values.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# symmetric int8 range shared with the KV quantization (ISSUE 9)
QCOMM_QMAX = 127.0

# default chunk width (elements per shared scale along the last axis).
# 128 keeps the scale overhead at 4/128 bytes/element (3.88x reduction)
# while a per-chunk outlier only costs its own 128 elements precision.
QCOMM_CHUNK = 128

COMM_DTYPES = ("fp32", "int8")


def quantized_psum(x, axis_name, *, chunk: int = QCOMM_CHUNK):
    """Sum `x` over the mapped mesh axis with int8 wire traffic.

    Drop-in for `jax.lax.psum(x, axis_name)` inside a shard_map body:
    `x` is this shard's partial sums (any float dtype, any shape with
    at least one axis); returns the allreduced sum at x's dtype.

    Two-level: per-(row, chunk) scales agree via `lax.pmax` (fp32,
    tiny), codes ride an int8-wide `lax.psum` (int32 accumulators —
    tp * 127 overflows int8, and a real ring requantizes per hop
    anyway), one fused dequant multiply at the end. Scales are
    per-shard-honest (pmax >= every local abs-max), so quantization
    never clips; rows quantize independently, so the result is
    batch-shape invariant (see module docstring).
    """
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    orig_dtype = x.dtype
    shape = x.shape
    width = shape[-1]
    c = min(int(chunk), int(width))
    rows = x.astype(jnp.float32).reshape(-1, width)         # [R, W]
    pad = (-width) % c
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    chunks = rows.reshape(rows.shape[0], -1, c)             # [R, C, c]
    local = jnp.max(jnp.abs(chunks), axis=-1) / QCOMM_QMAX  # [R, C]
    scale = jax.lax.pmax(local, axis_name)                  # shared, honest
    safe = jnp.maximum(scale, 1e-30)[..., None]
    codes = jnp.clip(jnp.round(chunks / safe),
                     -QCOMM_QMAX, QCOMM_QMAX).astype(jnp.int8)
    total = jax.lax.psum(codes.astype(jnp.int32), axis_name)
    out = total.astype(jnp.float32) * scale[..., None]
    out = out.reshape(rows.shape[0], -1)[:, :width]
    return out.reshape(shape).astype(orig_dtype)


def quantized_allgather(x, axis_name, *, chunk: int = QCOMM_CHUNK):
    """Gather the shards' last-axis slices with int8 wire traffic
    (ISSUE 19): the COLUMN-parallel collective, the other direction of
    `quantized_psum`. Inside a shard_map body over `axis_name`, `x` is
    this shard's [..., width] slice of a column-sharded activation
    (e.g. the lm_head's logits slice); returns the full
    [..., width * axis_size] value, tiled in axis-index order — exactly
    what `lax.all_gather(x, axis_name, axis=-1, tiled=True)` returns,
    at x's dtype.

    Same two-level shape as the psum: per-(row, chunk) scales agree via
    `lax.pmax` over the shards (fp32, tiny — and per-shard-honest, so
    quantizing at the shared scale never clips any shard's values),
    then only the int8 codes ride the wide all-gather, and ONE dequant
    multiply at the shared scale recovers every shard's slice. Chunking
    is along each row's last axis, never across rows, so the gathered
    value is BATCH-SHAPE INVARIANT like the psum's — the property that
    keeps engine streams token-exact vs their own oracle."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    orig_dtype = x.dtype
    shape = x.shape
    width = shape[-1]
    c = min(int(chunk), int(width))
    rows = x.astype(jnp.float32).reshape(-1, width)         # [R, W]
    pad = (-width) % c
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    chunks = rows.reshape(rows.shape[0], -1, c)             # [R, C, c]
    local = jnp.max(jnp.abs(chunks), axis=-1) / QCOMM_QMAX  # [R, C]
    scale = jax.lax.pmax(local, axis_name)                  # shared, honest
    safe = jnp.maximum(scale, 1e-30)[..., None]
    codes = jnp.clip(jnp.round(chunks / safe),
                     -QCOMM_QMAX, QCOMM_QMAX).astype(jnp.int8)
    wide = jax.lax.all_gather(codes, axis_name)             # [S, R, C, c]
    out = wide.astype(jnp.float32) * scale[None, ..., None]
    out = out.reshape(wide.shape[0], rows.shape[0], -1)[:, :, :width]
    out = jnp.moveaxis(out, 0, 1).reshape(rows.shape[0], -1)  # [R, S*W]
    return out.reshape(*shape[:-1], -1).astype(orig_dtype)


def quantized_allgather_reference(parts, *, chunk: int = QCOMM_CHUNK):
    """Host-side oracle of `quantized_allgather`: `parts` is the
    per-shard list of last-axis slices (all the same shape); returns
    the exact tiled value the shard_map primitive produces on every
    shard. Pure numpy, compared bit-for-bit by the unit tests."""
    parts = [np.asarray(p, np.float32) for p in parts]
    shape = parts[0].shape
    width = shape[-1]
    c = min(int(chunk), int(width))
    pad = (-width) % c
    rows = [p.reshape(-1, width) for p in parts]
    if pad:
        rows = [np.pad(r, ((0, 0), (0, pad))) for r in rows]
    chunks = [r.reshape(r.shape[0], -1, c) for r in rows]
    local = [np.abs(ch).max(axis=-1) / QCOMM_QMAX for ch in chunks]
    scale = np.maximum.reduce(local)                        # pmax
    safe = np.maximum(scale, 1e-30)[..., None]
    slices = []
    for ch in chunks:
        codes = np.clip(np.round(ch / safe),
                        -QCOMM_QMAX, QCOMM_QMAX).astype(np.int32)
        deq = codes.astype(np.float32) * scale[..., None]
        slices.append(deq.reshape(deq.shape[0], -1)[:, :width])
    out = np.concatenate(slices, axis=-1)
    return out.reshape(*shape[:-1], -1)


def allgather_bytes(rows: int, width: int, comm_dtype: str,
                    *, chunk: int = QCOMM_CHUNK) -> int:
    """Wire bytes ONE shard contributes to one column-parallel
    all-gather of its [rows, width] LOCAL slice — the serving
    `tp_gather_bytes` accounting (ISSUE 19). fp32: the shard ships its
    full slice at 4 bytes/element. int8: 1 code byte per element PLUS
    4 bytes per (row, chunk) shared scale — the scale pmax is wire
    traffic too, so it is counted, same honesty rule as
    `allreduce_bytes` (the committed reduction is 4/(1 + 4/chunk),
    never an assumed 4x)."""
    if comm_dtype not in COMM_DTYPES:
        raise ValueError(f"comm_dtype={comm_dtype!r}; expected one of "
                         f"{COMM_DTYPES}")
    rows, width = int(rows), int(width)
    if comm_dtype == "fp32":
        return rows * width * 4
    c = min(int(chunk), max(int(width), 1))
    n_chunks = -(-width // c)
    return rows * width + rows * n_chunks * 4


def quantized_allreduce_reference(parts, *, chunk: int = QCOMM_CHUNK):
    """Host-side oracle of `quantized_psum`: `parts` is the per-shard
    list of partial-sum arrays (all the same shape); returns the exact
    value the shard_map primitive produces on every shard. Pure numpy —
    the unit tests compare the two bit-for-bit."""
    parts = [np.asarray(p, np.float32) for p in parts]
    shape = parts[0].shape
    width = shape[-1]
    c = min(int(chunk), int(width))
    pad = (-width) % c
    rows = [p.reshape(-1, width) for p in parts]
    if pad:
        rows = [np.pad(r, ((0, 0), (0, pad))) for r in rows]
    chunks = [r.reshape(r.shape[0], -1, c) for r in rows]
    local = [np.abs(ch).max(axis=-1) / QCOMM_QMAX for ch in chunks]
    scale = np.maximum.reduce(local)                        # pmax
    safe = np.maximum(scale, 1e-30)[..., None]
    total = np.zeros_like(chunks[0], dtype=np.int32)
    for ch in chunks:
        total += np.clip(np.round(ch / safe),
                         -QCOMM_QMAX, QCOMM_QMAX).astype(np.int32)
    out = total.astype(np.float32) * scale[..., None]
    return out.reshape(total.shape[0], -1)[:, :width].reshape(shape)


def allreduce_bytes(rows: int, width: int, comm_dtype: str,
                    *, chunk: int = QCOMM_CHUNK) -> int:
    """Wire bytes ONE shard contributes to one row-parallel allreduce
    of a [rows, width] activation — the serving `tp_comm_bytes`
    accounting (host-side, CPU-countable, like the attention byte
    counters). fp32: the full payload at 4 bytes/element. int8: 1 code
    byte per element PLUS 4 bytes per (row, chunk) shared scale — the
    scale pmax is wire traffic too, so it is counted, and the
    committed reduction is 4/(1 + 4/chunk), never an assumed 4x."""
    if comm_dtype not in COMM_DTYPES:
        raise ValueError(f"comm_dtype={comm_dtype!r}; expected one of "
                         f"{COMM_DTYPES}")
    rows, width = int(rows), int(width)
    if comm_dtype == "fp32":
        return rows * width * 4
    c = min(int(chunk), max(int(width), 1))
    n_chunks = -(-width // c)
    return rows * width + rows * n_chunks * 4

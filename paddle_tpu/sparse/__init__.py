"""paddle.sparse — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ (SparseCooTensor/SparseCsrTensor creation,
unary/binary/matmul ops over phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA lowers sparse
contractions to gather/scatter + dense MXU tiles, which is how sparse is
done efficiently on TPU (there is no TPU CSR hardware path; the reference's
cuSPARSE world has no analogue).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor


class SparseCooTensor(Tensor):
    """A Tensor whose _value is a BCOO matrix."""

    @property
    def nnz(self):
        return int(self._value.nse)

    def indices(self):
        return Tensor._wrap(jnp.swapaxes(self._value.indices, 0, 1))

    def values(self):
        return Tensor._wrap(self._value.data)

    def to_dense(self):
        return Tensor._wrap(self._value.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def __repr__(self):
        return (f"SparseCooTensor(shape={list(self._value.shape)}, "
                f"nnz={self.nnz})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor — indices [ndim, nnz]."""
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from paddle_tpu.core.dtype import to_jax_dtype

        val = val.astype(to_jax_dtype(dtype))
    mat = jsparse.BCOO((val, jnp.swapaxes(idx, 0, 1)),
                       shape=tuple(shape) if shape is not None else None)
    out = SparseCooTensor.__new__(SparseCooTensor)
    Tensor.__init__(out, None, stop_gradient=stop_gradient)
    out._value = mat
    return out


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """CSR creation; stored as BCOO internally (converted from CSR triplets)."""
    crows = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = np.stack([rows, cols])
    return sparse_coo_tensor(idx, values, shape, dtype, stop_gradient)


def _coo_out(mat, stop_gradient=True):
    out = SparseCooTensor.__new__(SparseCooTensor)
    Tensor.__init__(out, None, stop_gradient=stop_gradient)
    out._value = mat
    return out


def matmul(x, y):
    """sparse @ dense (reference: sparse/matmul kernels)."""
    xv = x._value
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._wrap(xv @ yv)


def add(x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_out(jsparse.bcoo_sum_duplicates(
            jsparse.bcoo_concatenate([x._value, y._value], dimension=0)
            if False else _bcoo_add(x._value, y._value)))
    return Tensor._wrap(x._value.todense() + (
        y._value.todense() if isinstance(y, SparseCooTensor) else y._value))


def _bcoo_add(a, b):
    cat_data = jnp.concatenate([a.data, b.data])
    cat_idx = jnp.concatenate([a.indices, b.indices])
    out = jsparse.BCOO((cat_data, cat_idx), shape=a.shape)
    return jsparse.bcoo_sum_duplicates(out)


def _unary(fn):
    def op(x):
        v = x._value
        return _coo_out(jsparse.BCOO((fn(v.data), v.indices), shape=v.shape),
                        stop_gradient=x.stop_gradient)

    return op


relu = _unary(jax.nn.relu)
relu6 = _unary(lambda v: jnp.clip(v, 0, 6))
leaky_relu = _unary(lambda v: jnp.where(v >= 0, v, 0.01 * v))
abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
neg = _unary(jnp.negative)
expm1 = _unary(jnp.expm1)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)


def pow(x, factor):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    from paddle_tpu.core.dtype import to_jax_dtype

    v = x._value
    data = (v.data.astype(to_jax_dtype(value_dtype))
            if value_dtype is not None else v.data)
    idx = (v.indices.astype(to_jax_dtype(index_dtype))
           if index_dtype is not None else v.indices)
    return _coo_out(jsparse.BCOO((data, idx), shape=v.shape),
                    stop_gradient=x.stop_gradient)


def coalesce(x):
    """Merge duplicate indices (reference sparse_coo coalesce kernel)."""
    return _coo_out(jsparse.bcoo_sum_duplicates(x._value),
                    stop_gradient=x.stop_gradient)


def subtract(x, y):
    return add(x, _unary(jnp.negative)(y) if isinstance(y, SparseCooTensor)
               else Tensor._wrap(-y._value))


def multiply(x, y):
    """Elementwise; sparse*sparse intersects patterns (computed through the
    dense form — XLA fuses; TPU has no cuSPARSE-style path to save)."""
    xd = x._value.todense() if isinstance(x, SparseCooTensor) else x._value
    yd = y._value.todense() if isinstance(y, SparseCooTensor) else y._value
    return to_sparse_coo(Tensor._wrap(xd * yd))


def divide(x, y):
    """Structural-zero positions (zero in BOTH operands) yield 0, not NaN;
    a genuine value divided by zero still propagates inf."""
    xd = x._value.todense() if isinstance(x, SparseCooTensor) else x._value
    yd = y._value.todense() if isinstance(y, SparseCooTensor) else y._value
    both_zero = (xd == 0) & (yd == 0)
    return Tensor._wrap(jnp.where(both_zero, 0.0,
                                  xd / jnp.where(both_zero, 1.0, yd)))


def mv(x, vec):
    """sparse matrix @ dense vector (reference sparse/mv kernel)."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor._wrap(x._value @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta*input + alpha*(x @ y) (reference sparse/addmm kernel)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    iv = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    prod = xv @ yv                         # BCOO @ dense lowers via XLA
    iv = iv.todense() if isinstance(iv, jsparse.BCOO) else iv
    return Tensor._wrap(beta * iv + alpha * prod)


def masked_matmul(x, y, mask: "SparseCooTensor"):
    """(x @ y) evaluated ONLY at mask's nonzero positions (reference
    sparse/masked_matmul). TPU shape: gather the needed rows/cols and do
    per-nnz dot products — O(nnz*k) instead of O(m*n*k)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    idx = mask._value.indices                     # [nnz, 2]
    rows = jnp.take(xv, idx[:, 0], axis=0)        # [nnz, k]
    cols = jnp.take(yv, idx[:, 1], axis=1)        # [k, nnz]
    vals = jnp.sum(rows * jnp.swapaxes(cols, 0, 1), axis=-1)
    sg = (getattr(x, "stop_gradient", True)
          and getattr(y, "stop_gradient", True))
    return _coo_out(jsparse.BCOO((vals, idx), shape=mask._value.shape),
                    stop_gradient=sg)


def transpose(x, perm):
    v = x._value
    idx = v.indices[:, jnp.asarray(perm)]
    shape = tuple(v.shape[p] for p in perm)
    return _coo_out(jsparse.bcoo_sum_duplicates(
        jsparse.BCOO((v.data, idx), shape=shape)),
        stop_gradient=x.stop_gradient)


def reshape(x, shape):
    """Via linearized indices (pure index arithmetic, stays sparse)."""
    v = x._value
    old = jnp.asarray(v.shape)
    lin = jnp.zeros(v.nse, dtype=v.indices.dtype)
    for d in range(len(v.shape)):
        lin = lin * old[d] + v.indices[:, d]
    shape = [int(s) for s in shape]
    if shape.count(-1) > 1:
        raise ValueError("reshape accepts at most one -1 dim")
    if -1 in shape:
        total = int(np.prod(v.shape))
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = total // known
    shape = tuple(shape)
    new_idx = []
    rem = lin
    for s in reversed(shape):
        new_idx.append(rem % s)
        rem = rem // s
    idx = jnp.stack(list(reversed(new_idx)), axis=1)
    return _coo_out(jsparse.BCOO((v.data, idx.astype(v.indices.dtype)),
                                 shape=shape), stop_gradient=x.stop_gradient)


def sum(x, axis=None, keepdim=False):  # noqa: A001
    d = x._value.todense()
    return Tensor._wrap(jnp.sum(d, axis=axis, keepdims=keepdim))


def is_same_shape(x, y) -> bool:
    return tuple(x._value.shape) == tuple(y._value.shape)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def to_sparse_coo(dense: Tensor, sparse_dim=None):
    mat = jsparse.BCOO.fromdense(dense._value)
    return _coo_out(mat, stop_gradient=dense.stop_gradient)


from paddle_tpu.sparse import nn  # noqa: E402,F401


from builtins import slice as builtins_slice  # noqa: E402 — the sparse
# `slice` op below shadows the builtin


def isnan(x):
    """Elementwise NaN test on the stored values (reference
    sparse/unary.py isnan): pattern preserved, bool values."""
    v = x._value
    return _coo_out(jsparse.BCOO((jnp.isnan(v.data), v.indices),
                                 shape=v.shape))


def mask_as(x, mask, name=None):
    """Take dense x's values at `mask`'s sparsity pattern (reference
    sparse/binary.py mask_as)."""
    mv = mask._value
    xv = x._value if hasattr(x, "_value") else jnp.asarray(x)
    if hasattr(xv, "todense"):
        xv = xv.todense()
    data = xv[tuple(mv.indices.T)]
    return _coo_out(jsparse.BCOO((data, mv.indices), shape=mv.shape))


def slice(x, axes, starts, ends):  # noqa: A001
    """Slice a sparse tensor along `axes` (reference sparse/multiary.py
    slice): dense-form slice re-sparsified (pattern-changing op)."""
    v = x._value
    d = v.todense() if hasattr(v, "todense") else v
    idx = [builtins_slice(None)] * d.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = builtins_slice(st, en)
    return _coo_out(jsparse.BCOO.fromdense(d[tuple(idx)]))


builtins_slice = __builtins__["slice"] if isinstance(__builtins__, dict) \
    else getattr(__builtins__, "slice")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized low-rank PCA (reference sparse pca_lowrank /
    torch.pca_lowrank): returns (U, S, V) with q components."""
    from paddle_tpu.core.tensor import Tensor as _T

    v = x._value if hasattr(x, "_value") else jnp.asarray(x)
    if hasattr(v, "todense"):
        v = v.todense()
    m, n = v.shape[-2], v.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        v = v - jnp.mean(v, axis=-2, keepdims=True)
    from paddle_tpu.core.random import default_generator

    omega = jax.random.normal(default_generator.next_key(), (n, q),
                              jnp.float32)
    vT = jnp.swapaxes(v, -1, -2)      # batched-safe transpose
    y = v @ omega
    for _ in range(niter):
        y = v @ (vT @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(qmat, -1, -2) @ v
    u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return (_T._wrap(qmat @ u_b), _T._wrap(s),
            _T._wrap(jnp.swapaxes(vt, -1, -2)))

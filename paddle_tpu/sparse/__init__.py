"""paddle.sparse — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ (SparseCooTensor/SparseCsrTensor creation,
unary/binary/matmul ops over phi/kernels/sparse/).

TPU-native: backed by jax.experimental.sparse.BCOO — XLA lowers sparse
contractions to gather/scatter + dense MXU tiles, which is how sparse is
done efficiently on TPU (there is no TPU CSR hardware path; the reference's
cuSPARSE world has no analogue).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor


class SparseCooTensor(Tensor):
    """A Tensor whose _value is a BCOO matrix."""

    @property
    def nnz(self):
        return int(self._value.nse)

    def indices(self):
        return Tensor._wrap(jnp.swapaxes(self._value.indices, 0, 1))

    def values(self):
        return Tensor._wrap(self._value.data)

    def to_dense(self):
        return Tensor._wrap(self._value.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def __repr__(self):
        return (f"SparseCooTensor(shape={list(self._value.shape)}, "
                f"nnz={self.nnz})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor — indices [ndim, nnz]."""
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from paddle_tpu.core.dtype import to_jax_dtype

        val = val.astype(to_jax_dtype(dtype))
    mat = jsparse.BCOO((val, jnp.swapaxes(idx, 0, 1)),
                       shape=tuple(shape) if shape is not None else None)
    out = SparseCooTensor.__new__(SparseCooTensor)
    Tensor.__init__(out, None, stop_gradient=stop_gradient)
    out._value = mat
    return out


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """CSR creation; stored as BCOO internally (converted from CSR triplets)."""
    crows = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = np.stack([rows, cols])
    return sparse_coo_tensor(idx, values, shape, dtype, stop_gradient)


def _coo_out(mat, stop_gradient=True):
    out = SparseCooTensor.__new__(SparseCooTensor)
    Tensor.__init__(out, None, stop_gradient=stop_gradient)
    out._value = mat
    return out


def matmul(x, y):
    """sparse @ dense (reference: sparse/matmul kernels)."""
    xv = x._value
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._wrap(xv @ yv)


def add(x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_out(jsparse.bcoo_sum_duplicates(
            jsparse.bcoo_concatenate([x._value, y._value], dimension=0)
            if False else _bcoo_add(x._value, y._value)))
    return Tensor._wrap(x._value.todense() + (
        y._value.todense() if isinstance(y, SparseCooTensor) else y._value))


def _bcoo_add(a, b):
    cat_data = jnp.concatenate([a.data, b.data])
    cat_idx = jnp.concatenate([a.indices, b.indices])
    out = jsparse.BCOO((cat_data, cat_idx), shape=a.shape)
    return jsparse.bcoo_sum_duplicates(out)


def _unary(fn):
    def op(x):
        v = x._value
        return _coo_out(jsparse.BCOO((fn(v.data), v.indices), shape=v.shape),
                        stop_gradient=x.stop_gradient)

    return op


relu = _unary(jax.nn.relu)
abs = _unary(jnp.abs)  # noqa: A001
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
neg = _unary(jnp.negative)
expm1 = _unary(jnp.expm1)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def to_sparse_coo(dense: Tensor, sparse_dim=None):
    mat = jsparse.BCOO.fromdense(dense._value)
    return _coo_out(mat, stop_gradient=dense.stop_gradient)

"""paddle.sparse.nn — layers + functional over sparse COO tensors.

Reference: python/paddle/sparse/nn/ (Conv3D/SubmConv3D layer.py, ReLU,
BatchNorm, MaxPool3D, functional/conv.py, functional/transformer.py
sparse attention) over phi/kernels/sparse/ (gpu conv via gather-GEMM).

TPU-native design note: the reference's sparse conv builds a rulebook and
gathers active sites into dense GEMM tiles (cuSPARSE-free even on GPU).
Here the SUBMANIFOLD convs follow the same recipe when sparsity pays: at
active fraction < GATHER_THRESHOLD a host-resolved rulebook gathers the A
active sites' neighbor rows and one batched [K,A,Cin]x[K,Cin,Cout] GEMM
runs on the MXU — FLOPs proportional to active sites, not the grid
(_subm_gather_gemm). Denser inputs (and the pattern-changing Conv3D/2D)
compute through the dense form, where the MXU's appetite for large tiles
beats gather/scatter anyway; either way the SPARSE SEMANTICS hold:

  * Conv3D/Conv2D: output pattern = wherever the conv response is nonzero;
  * SubmConv3D/SubmConv2D: submanifold — output pattern is FORCED to the
    input's active sites (the defining property, Graham et al.), which is
    what keeps deep sparse CNNs from densifying layer by layer.

The sparse attention functional evaluates scores only at the mask's nnz
positions (per-nnz dots), the same contract as the reference's
sparse_attention kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer import Layer


def _dense(x):
    return x._value.todense() if hasattr(x._value, "todense") else x._value


def _sparsify(dense_val, stop_gradient=True):
    from paddle_tpu.sparse import _coo_out

    return _coo_out(jsparse.BCOO.fromdense(dense_val),
                    stop_gradient=stop_gradient)


def _active_mask(x):
    """[*, spatial..., 1] bool mask of the input's active sites (any channel
    nonzero)."""
    d = _dense(x)
    return jnp.any(d != 0, axis=-1, keepdims=True)


# ------------------------------------------------------------- functional

# active-fraction threshold below which the submanifold conv switches to
# gather-GEMM (the rulebook path): at high sparsity the A-row GEMMs beat
# the dense conv's full-grid FLOPs even on the MXU
GATHER_THRESHOLD = 0.125


def _gather_gemm_compute(feats_pad, nbr_idx, wk, bias_val):
    """Device arithmetic of the rulebook path (jittable, static shapes):
    feats_pad [A+1, Cin] (row 0 = zeros for missing neighbors),
    nbr_idx [K, A] int32 (-1 = missing), wk [K, Cin, Cout].
    Returns [A, Cout]. FLOPs ~ 2*K*A*Cin*Cout — proportional to ACTIVE
    sites, not the dense grid (the cost-model assert in
    tests/test_sparse_deep.py pins this)."""
    g = jnp.take(feats_pad, nbr_idx + 1, axis=0)       # [K, A, Cin]
    out = jnp.einsum("kac,kco->ao", g, wk,
                     preferred_element_type=jnp.float32).astype(
        feats_pad.dtype)
    if bias_val is not None:
        out = out + bias_val
    return out


def _subm_gather_gemm(d, w, bias_val, dilation, nd):
    """Submanifold conv computed ONLY at active sites — the TPU analogue
    of the reference's rulebook gather-GEMM sparse conv
    (phi/kernels/sparse/gpu/conv_kernel.cu, conv_grad_kernel.cu; Graham et
    al. submanifold sparse convnets): host numpy resolves each kernel
    offset's neighbor row per active site (eager-only, like every
    dynamic-shape op), then one batched GEMM per call runs on device.

    d: dense [N, *spatial, Cin]; w: [*kernel, Cin, Cout]. Returns the
    dense [N, *spatial, Cout] with only the input's active sites set."""
    ksizes = w.shape[:nd]
    cin, cout = w.shape[-2], w.shape[-1]
    dims = d.shape[:-1]                       # (N, *spatial)
    dh = np.asarray(d)
    mask = np.any(dh != 0, axis=-1)
    coords = np.argwhere(mask)                # [A, 1+nd]
    A = len(coords)
    out_shape = dims + (cout,)
    if A == 0:
        return jnp.zeros(out_shape, d.dtype)
    feats = jnp.asarray(dh[mask])             # [A, Cin]
    lin = np.ravel_multi_index(tuple(coords.T), dims)
    order = np.argsort(lin)
    lin_sorted = lin[order]
    offsets = np.stack(np.meshgrid(
        *[np.arange(k) for k in ksizes], indexing="ij"),
        -1).reshape(-1, nd)                   # [K, nd]
    # index-space offsets matching the dense path's SAME padding exactly:
    # tap m*dl - ((k-1)*dl)//2 — for even kernels with dilation this is
    # NOT (m - (k-1)//2)*dl (method='auto' must never change numerics)
    pad_left = np.asarray([((k - 1) * dl) // 2
                           for k, dl in zip(ksizes, dilation)])
    offsets = offsets * np.asarray(dilation) - pad_left
    K = len(offsets)
    nbr = np.full((K, A), -1, np.int64)
    for ki, off in enumerate(offsets):
        nc = coords.copy()
        nc[:, 1:] += off
        valid = np.all((nc[:, 1:] >= 0)
                       & (nc[:, 1:] < np.asarray(dims[1:])), axis=1)
        nlin = np.ravel_multi_index(
            tuple(np.where(valid[:, None], nc, 0).T), dims)
        pos = np.searchsorted(lin_sorted, nlin)
        pos = np.clip(pos, 0, A - 1)
        found = valid & (lin_sorted[pos] == nlin)
        nbr[ki] = np.where(found, order[pos], -1)
    feats_pad = jnp.concatenate(
        [jnp.zeros((1, cin), feats.dtype), feats])
    wk = jnp.asarray(w).reshape(K, cin, cout)
    out = _gather_gemm_compute(feats_pad, jnp.asarray(nbr, jnp.int32), wk,
                               bias_val)
    dense_out = jnp.zeros(out_shape, d.dtype)
    return dense_out.at[tuple(coords.T)].set(out.astype(d.dtype))


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             subm=False, method="auto"):
    """x: sparse [N, *spatial, Cin] (paddle sparse NDHWC/NHWC layout);
    weight dense [*kernel, Cin, Cout]. method: 'auto' picks gather-GEMM
    for submanifold convs whose active fraction is below
    GATHER_THRESHOLD, else the dense-form conv; 'gather'/'dense' force."""
    d = _dense(x)
    w = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilation, int):
        dilation = (dilation,) * nd
    if subm:
        # submanifold semantics (reference SubmConv): output sites == input
        # sites, which requires shape preservation — stride 1 + SAME padding
        # (the given padding is irrelevant to the active-site contract)
        if tuple(stride) != (1,) * nd:
            raise ValueError("submanifold conv requires stride=1 "
                             "(output sites must equal input sites)")
        if groups == 1 and method != "dense":
            b_val = None
            if bias is not None:
                b_val = (bias._value if isinstance(bias, Tensor)
                         else jnp.asarray(bias))
            # one scalar readback (not a full device->host transfer) to
            # pick the method on the auto path
            if method == "gather" or (
                    float(jnp.mean(jnp.any(d != 0, axis=-1)))
                    < GATHER_THRESHOLD):
                return _sparsify(_subm_gather_gemm(d, w, b_val, dilation,
                                                   nd))
        padding = [((k - 1) * dl // 2, (k - 1) * dl - (k - 1) * dl // 2)
                   for k, dl in zip(w.shape[:nd], dilation)]
    elif isinstance(padding, int):
        padding = [(padding, padding)] * nd
    elif padding and isinstance(padding[0], int):
        padding = [(p, p) for p in padding]
    spatial = "DHW"[-nd:]
    lhs_spec = "N" + spatial + "C"
    rhs_spec = spatial + "IO"
    dn = jax.lax.conv_dimension_numbers(d.shape, w.shape,
                                        (lhs_spec, rhs_spec, lhs_spec))
    out = jax.lax.conv_general_dilated(
        d, w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        b = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + b
    if subm:
        # submanifold: only the input's active sites stay active
        out = jnp.where(_active_mask(x), out, 0.0)
    return _sparsify(out)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, method="auto"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    subm=True, method=method)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, method="auto"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    subm=True, method=method)


def max_pool3d(x, kernel_size, stride=None, padding=0, data_format="NDHWC"):
    """Max over ACTIVE sites only (reference sparse maxpool kernel):
    structural zeros must not dominate all-negative active values, so
    inactive sites enter the window as -inf; empty windows yield 0."""
    d = _dense(x)
    d = jnp.where(_active_mask(x), d, -jnp.inf)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * 3
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    out = jax.lax.reduce_window(
        d, -jnp.inf, jax.lax.max,
        window_dimensions=(1,) + tuple(kernel_size) + (1,),
        window_strides=(1,) + tuple(stride) + (1,),
        padding=[(0, 0)] + list(padding) + [(0, 0)])
    out = jnp.where(jnp.isfinite(out), out, 0.0)
    return _sparsify(out)


def relu(x):
    from paddle_tpu import sparse as S

    return S.relu(x)


def softmax(x, axis=-1):
    """Sparse softmax: normalizes over the nonzeros of each row (reference
    sparse/softmax kernel semantics — zeros are structural, not values)."""
    v = x._value
    if axis not in (-1, v.indices.shape[1] - 1):
        raise ValueError("sparse softmax supports the last axis only")
    d = _dense(x)
    mask = d != 0
    scores = jnp.where(mask, d, -jnp.inf)
    out = jax.nn.softmax(scores, axis=-1)
    out = jnp.where(mask, out, 0.0)
    return _sparsify(out, stop_gradient=x.stop_gradient)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None):
    """Sparse-pattern attention (reference
    python/paddle/sparse/nn/functional/transformer.py:attention): scores
    evaluated only at sparse_mask's nnz; softmax over each row's nnz.

    query/key/value: dense [B, H, S, D]; sparse_mask: SparseCooTensor
    [B*H, S, S] giving the allowed attention pattern.
    """
    q = query._value if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    b, h, s, d = q.shape
    idx = sparse_mask._value.indices              # [nnz, 3] (bh, qi, ki)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    q_rows = qf[idx[:, 0], idx[:, 1]]             # [nnz, d]
    k_rows = kf[idx[:, 0], idx[:, 2]]
    scores = jnp.sum(q_rows * k_rows, axis=-1) / jnp.sqrt(float(d))
    if key_padding_mask is not None:
        # additive float mask [B, S] applied at each nnz's key position
        kp = (key_padding_mask._value
              if isinstance(key_padding_mask, Tensor)
              else jnp.asarray(key_padding_mask))
        scores = scores + kp[idx[:, 0] // h, idx[:, 2]]
    if attn_mask is not None:
        am = (attn_mask._value if isinstance(attn_mask, Tensor)
              else jnp.asarray(attn_mask))
        scores = scores + am[idx[:, 1], idx[:, 2]]
    # segment softmax over (bh, qi) groups
    seg = idx[:, 0] * s + idx[:, 1]
    nseg = b * h * s
    seg_max = jax.ops.segment_max(scores, seg, num_segments=nseg)
    p = jnp.exp(scores - seg_max[seg])
    seg_sum = jax.ops.segment_sum(p, seg, num_segments=nseg)
    p = p / jnp.maximum(seg_sum[seg], 1e-30)
    contrib = p[:, None] * vf[idx[:, 0], idx[:, 2]]   # [nnz, d]
    out = jax.ops.segment_sum(contrib, seg, num_segments=nseg)
    return Tensor._wrap(out.reshape(b, h, s, d))


class functional:
    """namespace shim: paddle.sparse.nn.functional.*"""

    conv3d = staticmethod(conv3d)
    subm_conv3d = staticmethod(subm_conv3d)
    conv2d = staticmethod(conv2d)
    subm_conv2d = staticmethod(subm_conv2d)
    max_pool3d = staticmethod(max_pool3d)
    relu = staticmethod(relu)
    softmax = staticmethod(softmax)
    attention = staticmethod(attention)


# ---------------------------------------------------------------- layers

class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, subm,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode=None,
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * nd
        self._nd = nd
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self.weight = self.create_parameter(
            list(kernel_size) + [in_channels // groups, out_channels],
            default_initializer=weight_attr or I.XavierUniform())
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], is_bias=True))

    def forward(self, x):
        return _conv_nd(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._nd, subm=self._subm)


class Conv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, 3, False,
                         **kw)


class SubmConv3D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.pop("key", None)
        super().__init__(in_channels, out_channels, kernel_size, 3, True,
                         **kw)


class Conv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, 2, False,
                         **kw)


class SubmConv2D(_SparseConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        kw.pop("key", None)
        super().__init__(in_channels, out_channels, kernel_size, 2, True,
                         **kw)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC"):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        return max_pool3d(x, self._k, self._s, self._p)


class ReLU(Layer):
    def forward(self, x):
        return relu(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return softmax(x, self._axis)


class BatchNorm(Layer):
    """BatchNorm over the values of active sites only (reference
    sparse/nn/layer/norm.py: statistics from nnz values, not zeros)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        self._eps = epsilon
        self._momentum = momentum
        self.weight = self.create_parameter(
            [num_features], default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer("_mean",
                             Tensor._wrap(jnp.zeros(num_features)))
        self.register_buffer("_variance",
                             Tensor._wrap(jnp.ones(num_features)))

    def forward(self, x):
        v = x._value
        nc = int(self.weight._value.shape[0])
        if v.data.ndim == 2:
            # n_dense=1 layout: data [nnz, C]
            vals, ch = v.data, None
        else:
            # scalar entries (fromdense default): channel = last index col
            vals, ch = v.data, v.indices[:, -1]
        if self.training:
            if ch is None:
                mean = jnp.mean(vals, axis=0)
                var = jnp.var(vals, axis=0)
            else:
                cnt = jnp.maximum(
                    jax.ops.segment_sum(jnp.ones_like(vals), ch,
                                        num_segments=nc), 1.0)
                mean = jax.ops.segment_sum(vals, ch, num_segments=nc) / cnt
                var = jax.ops.segment_sum(
                    jnp.square(vals - mean[ch]), ch, num_segments=nc) / cnt
            m = self._momentum
            self._mean._value = m * self._mean._value + (1 - m) * mean
            self._variance._value = (m * self._variance._value
                                     + (1 - m) * var)
        else:
            mean, var = self._mean._value, self._variance._value
        w, b = self.weight._value, self.bias._value
        if ch is not None:
            mean, var, w, b = mean[ch], var[ch], w[ch], b[ch]
        out = (vals - mean) * jax.lax.rsqrt(var + self._eps) * w + b
        from paddle_tpu.sparse import _coo_out

        return _coo_out(jsparse.BCOO((out, v.indices), shape=v.shape),
                        stop_gradient=x.stop_gradient)

"""String tensors + kernels.

Reference: paddle/phi/core/string_tensor.h + kernels/strings/ (the phi
strings surface is small: lower/upper case conversion with an optional
utf8 mode, plus construction/copy).

TPU-native reading: strings never touch the MXU — the reference runs
these kernels on CPU too. StringTensor here wraps a numpy object array on
host with the same API shape (shape/numpy/lower/upper), keeping parity for
text preprocessing pipelines feeding tokenized int tensors to the device.
"""

from __future__ import annotations

import numpy as np


class StringTensor:
    """A host-side tensor of python strings (phi StringTensor analogue)."""

    def __init__(self, data, name: str = ""):
        if isinstance(data, StringTensor):
            self._data = data._data.copy()
        else:
            self._data = np.asarray(data, dtype=object)
        self.name = name

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self) -> np.ndarray:
        return self._data

    def __getitem__(self, idx):
        out = self._data[idx]
        return StringTensor(out) if isinstance(out, np.ndarray) else out

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == o)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def _map(st: StringTensor, fn) -> StringTensor:
    flat = [fn(s) for s in st._data.reshape(-1)]
    return StringTensor(
        np.asarray(flat, dtype=object).reshape(st._data.shape))


def to_string_tensor(data, name: str = "") -> StringTensor:
    """Construction kernel (phi strings empty/copy family)."""
    return StringTensor(data, name)


def lower(st: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """phi strings lower kernel. use_utf8_encoding=False restricts to
    ASCII case folding like the reference's charcases mode."""
    if use_utf8_encoding:
        return _map(st, str.lower)
    return _map(st, lambda s: "".join(
        c.lower() if ord(c) < 128 else c for c in s))


def upper(st: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """phi strings upper kernel."""
    if use_utf8_encoding:
        return _map(st, str.upper)
    return _map(st, lambda s: "".join(
        c.upper() if ord(c) < 128 else c for c in s))

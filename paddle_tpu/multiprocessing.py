"""paddle.multiprocessing — Tensor sharing across processes.

Reference: python/paddle/incubate/multiprocessing/reductions.py — registers
ForkingPickler reductions so Tensors ride mp.Queue/Pipe via the
file_system sharing strategy (CUDA IPC handles on GPU).

TPU-native: device buffers are PJRT-owned and not IPC-shareable, so a
Tensor crosses process boundaries through the file_system strategy: the
producer writes the host array to a file under /dev/shm (RAM-backed) and
pickles only the filename; the consumer maps it and DELETES it after
copying (consumer-owns-cleanup, so a producer exiting right after
queue.put — the standard worker pattern — can never race the unlink).
A message that is never consumed leaves a file until /dev/shm is swept,
the same trade-off the reference's file_system strategy makes.
"""

from __future__ import annotations

import os
import tempfile
from multiprocessing import *  # noqa: F401,F403
from multiprocessing import reduction

import numpy as np

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def _dtype_by_name(name):
    """np.dtype by NAME, not .str — ml_dtypes (bfloat16, float8_*) encode
    as opaque '<V2' through .str and would arrive as raw void."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _rebuild_tensor(path, shape, dtype_name):
    from paddle_tpu.core.tensor import Tensor

    try:
        arr = np.fromfile(path,
                          dtype=_dtype_by_name(dtype_name)).reshape(shape)
    except FileNotFoundError:
        raise RuntimeError(
            f"paddle_tpu.multiprocessing: shared-memory segment {path!r} is "
            "gone — a Tensor message can be deserialized only ONCE (the "
            "first consumer unlinks the segment). Re-pickling the same "
            "bytes or fanning one message out to several consumers is not "
            "supported by the file_system strategy; send one message per "
            "consumer instead.") from None
    try:
        os.unlink(path)  # consumer owns cleanup
    except OSError:
        pass
    return Tensor._wrap(arr)


def _reduce_tensor(tensor):
    arr = np.asarray(tensor._value)
    fd, path = tempfile.mkstemp(prefix="paddle_tpu_shm_", dir=_SHM_DIR)
    with os.fdopen(fd, "wb") as f:
        arr.tofile(f)
    return _rebuild_tensor, (path, arr.shape, arr.dtype.name)


def init_reductions():
    from paddle_tpu.core.tensor import Parameter, Tensor

    reduction.ForkingPickler.register(Tensor, _reduce_tensor)
    reduction.ForkingPickler.register(Parameter, _reduce_tensor)


init_reductions()

"""paddle.save / paddle.load equivalent.

Reference: python/paddle/framework/io.py:773/1020 — pickled nested
state_dicts. Here tensors serialize as numpy arrays inside a pickle; loading
re-wraps them as device tensors lazily (host arrays until first use keeps load
cheap on big checkpoints).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from paddle_tpu.core.tensor import Tensor


def _pack(obj: Any):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "value": obj.numpy(),
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any):
    import jax.numpy as jnp

    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            return Tensor(jnp.asarray(obj["value"]),
                          stop_gradient=obj["stop_gradient"])
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **kwargs):
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))

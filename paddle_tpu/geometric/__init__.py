"""paddle.geometric — graph message passing.

Reference: python/paddle/geometric/ (send_u_recv/send_ue_recv over
graph_send_recv kernels, segment ops).

TPU-native: segment reductions via jax.ops.segment_* — XLA lowers to sorted
scatter-adds which tile well; no custom kernels needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import OPS, OpDef, make_op_function


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    n = out_size if out_size is not None else x.shape[0]
    msgs = jnp.take(x, src_index, axis=0)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst_index, num_segments=n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, dst_index, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst_index, x.dtype),
                                  dst_index, num_segments=n)
        return s / jnp.maximum(cnt, 1)[:, None]
    if reduce_op == "max":
        return jax.ops.segment_max(msgs, dst_index, num_segments=n)
    if reduce_op == "min":
        return jax.ops.segment_min(msgs, dst_index, num_segments=n)
    raise ValueError(reduce_op)


def _send_ue_recv(x, e, src_index, dst_index, message_op="add",
                  reduce_op="sum", out_size=None):
    """Node+edge message passing (reference send_ue_recv,
    phi/kernels/gpu/graph_send_ue_recv_kernel.cu): msg = x[src] OP e,
    segment-reduced at dst. message_op: add/sub/mul/div."""
    msgs = jnp.take(x, src_index, axis=0)
    if message_op == "add":
        msgs = msgs + e
    elif message_op == "sub":
        msgs = msgs - e
    elif message_op == "mul":
        msgs = msgs * e
    elif message_op == "div":
        msgs = msgs / e
    else:
        raise ValueError(message_op)
    n = out_size if out_size is not None else x.shape[0]
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst_index, num_segments=n)
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msgs, dst_index, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst_index, x.dtype),
                                  dst_index, num_segments=n)
        return s / jnp.maximum(cnt, 1)[:, None]
    if reduce_op == "max":
        return jax.ops.segment_max(msgs, dst_index, num_segments=n)
    if reduce_op == "min":
        return jax.ops.segment_min(msgs, dst_index, num_segments=n)
    raise ValueError(reduce_op)


def _segment_sum(x, segment_ids, num_segments=None):
    n = num_segments if num_segments is not None else int(segment_ids.max()) + 1
    return jax.ops.segment_sum(x, segment_ids, num_segments=n)


def _segment_mean(x, segment_ids, num_segments=None):
    n = num_segments if num_segments is not None else int(segment_ids.max()) + 1
    s = jax.ops.segment_sum(x, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones(x.shape[0], x.dtype), segment_ids,
                              num_segments=n)
    shape = (-1,) + (1,) * (x.ndim - 1)
    return s / jnp.maximum(cnt, 1).reshape(shape)


def _segment_max(x, segment_ids, num_segments=None):
    n = num_segments if num_segments is not None else int(segment_ids.max()) + 1
    return jax.ops.segment_max(x, segment_ids, num_segments=n)


def _segment_min(x, segment_ids, num_segments=None):
    n = num_segments if num_segments is not None else int(segment_ids.max()) + 1
    return jax.ops.segment_min(x, segment_ids, num_segments=n)


for _name, _fn in (("send_u_recv", _send_u_recv),
                   ("send_ue_recv", _send_ue_recv),
                   ("segment_sum", _segment_sum),
                   ("segment_mean", _segment_mean),
                   ("segment_max", _segment_max),
                   ("segment_min", _segment_min)):
        # dynamic=True: default num_segments derives from concrete index values
    # (pass num_segments/out_size explicitly inside jit-traced code)
    OPS.setdefault(f"geo_{_name}", OpDef(f"geo_{_name}", _fn, diff=True,
                                         dynamic=True, method=False))
    # also registered under the reference kernel name (graph_send_* family)
    OPS.setdefault(_name, OpDef(_name, _fn, diff=True, dynamic=True,
                                method=False))

send_u_recv = make_op_function("geo_send_u_recv")
send_ue_recv = make_op_function("geo_send_ue_recv")
segment_sum = make_op_function("geo_segment_sum")
segment_mean = make_op_function("geo_segment_mean")
segment_max = make_op_function("geo_segment_max")
segment_min = make_op_function("geo_segment_min")

from paddle_tpu.geometric.sampling import (  # noqa: F401,E402
    khop_sampler, reindex_graph, sample_neighbors, send_uv,
    weighted_sample_neighbors,
)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference
    geometric.reindex_heter_graph): per-relation neighbor lists share ONE
    node mapping. Relations are reindexed one by one against the mapping
    accumulated over all of them, preserving each relation's per-node
    counts (per-relation dst stays correct for non-uniform counts)."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.tensor import Tensor

    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    ns = [np.asarray(n._value if isinstance(n, Tensor) else n)
          for n in neighbors]
    cs = [np.asarray(c._value if isinstance(c, Tensor) else c)
          for c in count]
    # one shared mapping: input nodes first, then first-seen neighbors
    mapping = {int(v): i for i, v in enumerate(xv)}
    order = list(xv)
    for n in ns:
        for v in n:
            if int(v) not in mapping:
                mapping[int(v)] = len(order)
                order.append(int(v))
    reindexed = []
    dsts = []
    for n, c in zip(ns, cs):
        reindexed.append(Tensor._wrap(jnp.asarray(
            [mapping[int(v)] for v in n], dtype=jnp.int32)))
        dsts.append(Tensor._wrap(jnp.asarray(
            np.repeat(np.arange(len(xv)), c), dtype=jnp.int32)))
    nodes = Tensor._wrap(jnp.asarray(order, dtype=jnp.int32))
    return reindexed, dsts, nodes

"""Graph sampling & reindex — paddle.geometric sampling family.

Reference: python/paddle/geometric/sampling/neighbors.py (sample_neighbors
:68, weighted_sample_neighbors:256), reindex.py:34, incubate
graph_khop_sampler, message_passing/send_recv.py:413 (send_uv) over the
phi graph_sample_neighbors / graph_reindex / graph_khop_sampler kernels.

TPU-native split: neighbor sampling produces DYNAMIC-size outputs and
feeds the input pipeline, so it runs host-side on numpy (same place the
reference runs it for CPUPlace); `send_uv` is dense gather+op math and
runs on device, differentiably, through the dispatcher.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import OPS, OpDef, dispatch, host_only_impl


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


def _wrap(x):
    return Tensor._wrap(jnp.asarray(x))


def _sample_one(row, colptr, node, k, rng, weight=None):
    beg, end = int(colptr[node]), int(colptr[node + 1])
    neigh = row[beg:end]
    if k < 0 or len(neigh) <= k:
        return neigh, np.arange(beg, end)
    if weight is None:
        pick = rng.choice(len(neigh), size=k, replace=False)
    else:
        wv = weight[beg:end].astype(np.float64)
        if wv.sum() > 0:
            p = wv / wv.sum()
            # zero-weight edges are unsampleable: cap k at the nonzero count
            k = min(k, int((wv > 0).sum()))
            pick = rng.choice(len(neigh), size=k, replace=False, p=p)
        else:
            pick = rng.choice(len(neigh), size=k, replace=False)
    return neigh[pick], beg + pick


def _sample_impl(row, colptr, input_nodes, sample_size, eids, return_eids,
                 weight=None):
    rv, cv, nv = _np(row), _np(colptr), _np(input_nodes)
    ev = _np(eids) if eids is not None else None
    wv = _np(weight) if weight is not None else None
    rng = np.random.default_rng()
    outs, cnts, oeids = [], [], []
    for node in nv:
        neigh, idx = _sample_one(rv, cv, int(node), int(sample_size), rng,
                                 weight=wv)
        outs.append(neigh)
        cnts.append(len(neigh))
        if return_eids:
            oeids.append(ev[idx] if ev is not None else idx)
    out = _wrap(np.concatenate(outs) if outs else np.zeros(0, rv.dtype))
    cnt = _wrap(np.asarray(cnts, np.int32))
    if return_eids:
        return out, cnt, _wrap(np.concatenate(oeids) if oeids
                               else np.zeros(0, np.int64))
    return out, cnt


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling on a CSC graph. Returns
    (out_neighbors, out_count[, out_eids])."""
    return _sample_impl(row, colptr, input_nodes, sample_size, eids,
                        return_eids)


OPS.setdefault("graph_sample_neighbors",
               OpDef("graph_sample_neighbors",
                     host_only_impl("graph_sample_neighbors",
                                    "paddle_tpu.geometric.sample_neighbors"),
                     diff=False,
                     dynamic=True, method=False))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional sampling without replacement (reference
    weighted_sample_neighbors — A-Res reservoir there, np.choice here)."""
    return _sample_impl(row, colptr, input_nodes, sample_size, eids,
                        return_eids, weight=edge_weight)


OPS.setdefault("weighted_sample_neighbors",
               OpDef("weighted_sample_neighbors",
                     host_only_impl(
                         "weighted_sample_neighbors",
                         "paddle_tpu.geometric.weighted_sample_neighbors"),
                     diff=False, dynamic=True, method=False))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Relabel sampled subgraph nodes to dense local ids. Returns
    (reindex_src, reindex_dst, out_nodes) — reference reindex.py:34."""
    xv, nbv, cv = _np(x), _np(neighbors), _np(count)
    out_nodes = list(xv.tolist())
    seen = {int(n): i for i, n in enumerate(xv)}
    src = np.empty(len(nbv), np.int64)
    for i, n in enumerate(nbv.tolist()):
        if n not in seen:
            seen[n] = len(out_nodes)
            out_nodes.append(n)
        src[i] = seen[n]
    dst = np.repeat(np.arange(len(xv)), cv)
    return (_wrap(src), _wrap(dst.astype(np.int64)),
            _wrap(np.asarray(out_nodes, xv.dtype)))


OPS.setdefault("reindex_graph", OpDef(
    "reindex_graph", host_only_impl("reindex_graph",
                                    "paddle_tpu.geometric.reindex_graph"),
                                      diff=False, dynamic=True,
                                      method=False))


def khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None,
                 return_eids=False, name=None):
    """Multi-hop sampling (incubate graph_khop_sampler): chain
    sample_neighbors per hop, then one reindex over the union. Returns
    (edge_src, edge_dst, sample_index, reindex_x[, edge_eids])."""
    cur = input_nodes
    all_neigh, all_cnt, all_eids = [], [], []
    base = [_np(input_nodes)]
    for k in sample_sizes:
        res = sample_neighbors(row, colptr, cur, sample_size=k,
                               eids=sorted_eids, return_eids=return_eids)
        neigh, cnt = res[0], res[1]
        all_neigh.append(_np(neigh))
        all_cnt.append((_np(cur), _np(cnt)))
        if return_eids:
            all_eids.append(_np(res[2]))
        base.append(_np(neigh))
        cur = neigh
    # union in first-seen order; edges expressed in local ids
    seen, order = {}, []

    def local(n):
        if n not in seen:
            seen[n] = len(order)
            order.append(n)
        return seen[n]

    for n in base[0].tolist():
        local(int(n))
    src, dst = [], []
    for (nodes, cnts), neigh in zip(all_cnt, all_neigh):
        pos = 0
        for node, c in zip(nodes.tolist(), cnts.tolist()):
            d = local(int(node))
            for m in neigh[pos:pos + c].tolist():
                src.append(local(int(m)))
                dst.append(d)
            pos += c
    sample_index = np.asarray(order, np.int64)
    reindex_x = np.asarray([seen[int(n)] for n in base[0]], np.int64)
    outs = (_wrap(np.asarray(src, np.int64)),
            _wrap(np.asarray(dst, np.int64)),
            _wrap(sample_index), _wrap(reindex_x))
    if return_eids:
        return outs + (_wrap(np.concatenate(all_eids) if all_eids
                             else np.zeros(0, np.int64)),)
    return outs


OPS.setdefault("graph_khop_sampler",
               OpDef("graph_khop_sampler",
                     host_only_impl("graph_khop_sampler",
                                    "paddle_tpu.geometric.khop_sampler"),
                     diff=False,
                     dynamic=True, method=False))


def _send_uv(x, y, src_index, dst_index, message_op="add"):
    xs = jnp.take(x, src_index, axis=0)
    ys = jnp.take(y, dst_index, axis=0)
    if message_op == "add":
        return xs + ys
    if message_op == "sub":
        return xs - ys
    if message_op == "mul":
        return xs * ys
    if message_op == "div":
        return xs / ys
    raise ValueError(message_op)


OPS.setdefault("send_uv", OpDef("send_uv", _send_uv, diff=True,
                                method=False))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] op y[dst] — dense gather, device-side,
    differentiable (reference send_recv.py:413)."""
    as_t = lambda v: v if isinstance(v, Tensor) else _wrap(v)
    return dispatch("send_uv", (x, y, as_t(src_index), as_t(dst_index)),
                    {"message_op": message_op})

"""paddle.version (reference python/paddle/version/__init__.py —
generated at build time there; static here)."""

full_version = "0.1.0"
major, minor, patch = full_version.split(".")
rc = "0"
commit = "paddle-tpu"
istaged = True
with_pip_cuda_libraries = "OFF"
cuda_archs = []


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("tpu: True (XLA/PJRT build)")
    print("cuda: False")
    print("cudnn: False")


def cuda():
    return False


def cudnn():
    return False


def xpu():
    return False


def nccl():
    return "0"


def show_ipu():
    return None

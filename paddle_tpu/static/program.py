"""Static-graph Program: a recorded op tape compiled to one XLA executable.

Reference architecture being mirrored:
  - Program/Block graph building under program_guard
    (python/paddle/base/framework.py Program:5890, static/__init__)
  - shape inference while building: paddle/phi/infermeta/* -> here
    jax.eval_shape over the op impl (same function both universes)
  - execution: StandaloneExecutor/PirInterpreter
    (fluid/framework/new_executor/) -> here the whole Program replays inside
    ONE jax.jit, which is where TPUs want the static universe to live
    (SURVEY.md §7 step 4): no instruction-level interpreter, no stream
    analysis — XLA owns scheduling.

Mechanics: under program_guard, `static.data` creates symbolic Tensors
(abstract aval, no buffer). The eager dispatcher routes any op touching a
symbolic tensor to Program.record, which appends a node and returns symbolic
outputs shaped by eval_shape. Executor.run jit-compiles the replay, keyed by
feed signatures.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from paddle_tpu.core import dtype as dtype_mod
from paddle_tpu.core.tensor import Tensor


class Node:
    __slots__ = ("op_name", "args_tpl", "kwargs_tpl", "input_ids", "out_ids",
                 "impl")

    def __init__(self, op_name, args_tpl, kwargs_tpl, input_ids, out_ids,
                 impl=None):
        self.op_name = op_name
        self.args_tpl = args_tpl
        self.kwargs_tpl = kwargs_tpl
        self.input_ids = input_ids
        self.out_ids = out_ids
        # impl: set for direct (unregistered) ops — e.g. recompute segments —
        # whose name has no OPS entry to look up at replay
        self.impl = impl


class Program:
    """Reference: base/framework.py Program:5890 (single-block form)."""

    def __init__(self):
        self.nodes: List[Node] = []
        self.feeds: Dict[str, int] = {}      # name -> value id
        self.avals: Dict[int, jax.ShapeDtypeStruct] = {}
        self.constants: Dict[int, Any] = {}  # value id -> concrete array
        # value id -> live Tensor (parameters): reads current value each run
        self.const_tensors: Dict[int, Any] = {}
        self.rng_slots: List[int] = []       # value ids fed fresh keys per run
        self._next_id = 0
        self.grad_map: Dict[int, int] = {}   # primal value id -> grad value id

    def new_value(self, aval) -> int:
        vid = self._next_id
        self._next_id += 1
        self.avals[vid] = aval
        return vid

    def add_feed(self, name, shape, dtype) -> "Tensor":
        from paddle_tpu.ops.registry import STATIC_SEEN

        STATIC_SEEN[0] = True
        aval = jax.ShapeDtypeStruct(tuple(0 if s in (-1, None) else s
                                          for s in shape),
                                    dtype_mod.to_jax_dtype(dtype))
        vid = self.new_value(aval)
        self.feeds[name] = vid
        t = Tensor.__new__(Tensor)
        Tensor.__init__(t, None, stop_gradient=True, name=name)
        t._value = _Symbolic(self, vid, aval)
        return t

    def clone(self, for_test=False):
        """Independent copy (fresh node/constant containers; array values
        shared). for_test=True rewrites training-mode attrs (dropout) to
        inference behavior — the reference's clone(for_test=True) pruning."""
        new = Program()
        new.feeds = dict(self.feeds)
        new.avals = dict(self.avals)
        new.constants = dict(self.constants)
        new.const_tensors = dict(self.const_tensors)
        new.rng_slots = list(self.rng_slots)
        new._next_id = self._next_id
        new.grad_map = dict(self.grad_map)
        for n in self.nodes:
            kwargs_tpl = n.kwargs_tpl
            if for_test and n.op_name == "dropout":
                kwargs_tpl = tuple(
                    (k, False if k == "training" else v)
                    for k, v in kwargs_tpl)
            new.nodes.append(Node(n.op_name, n.args_tpl, kwargs_tpl,
                                  list(n.input_ids), list(n.out_ids),
                                  impl=n.impl))
        return new

    def __repr__(self):
        return (f"Program(nodes={len(self.nodes)}, feeds={list(self.feeds)})")

    def current_constants(self) -> Dict[int, Any]:
        """Constant values with live parameter tensors re-read (so optimizer
        updates between runs take effect)."""
        out = dict(self.constants)
        for vid, t in self.const_tensors.items():
            out[vid] = t._value
        return out

    # ---------------------------------------------------------------- replay

    def replay(self, feed_values: Dict[str, Any], fetch_ids: Sequence[int],
               constants: Optional[Dict[int, Any]] = None,
               rng_keys: Optional[Sequence[Any]] = None):
        """constants override lets the Executor pass parameter values as jit
        INPUTS (not baked weights); rng_keys feed fresh randomness per run."""
        from paddle_tpu.ops.registry import OPS, _fill

        env: Dict[int, Any] = dict(self.constants)
        if constants is not None:
            env.update(constants)
        if rng_keys is not None:
            for vid, key in zip(self.rng_slots, rng_keys):
                env[vid] = key
        for name, vid in self.feeds.items():
            env[vid] = feed_values[name]
        for node in self.nodes:
            tvals = [env[i] for i in node.input_ids]
            kwargs = {k: _fill(v, tvals) for k, v in node.kwargs_tpl}
            impl = node.impl if node.impl is not None else OPS[node.op_name].impl
            out = impl(*_fill(node.args_tpl, tvals), **kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            for vid, o in zip(node.out_ids, outs):
                env[vid] = o
        return tuple(env[i] for i in fetch_ids)


class _Symbolic:
    """Stand-in for a jax value inside a Program: shape/dtype only."""

    __slots__ = ("program", "vid", "aval")

    def __init__(self, program, vid, aval):
        self.program = program
        self.vid = vid
        self.aval = aval

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    def __repr__(self):
        return f"<symbolic {self.aval.shape} {self.aval.dtype} v{self.vid}>"


_default_main_program: Optional[Program] = None
_default_startup_program: Program = Program()


def default_main_program() -> Program:
    global _default_main_program
    if _default_main_program is None:
        _default_main_program = Program()
    return _default_main_program


def default_startup_program() -> Program:
    return _default_startup_program


def in_static_build() -> bool:
    return _default_main_program is not None and _building


_building = False


@contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    global _default_main_program, _building
    prev, prev_b = _default_main_program, _building
    _default_main_program = main_program
    _building = True
    try:
        yield main_program
    finally:
        _default_main_program, _building = prev, prev_b


def record_dispatch(name: str, args, kwargs, _op=None) -> Any:
    """Called by the eager dispatcher when an input is symbolic. `_op`: an
    unregistered OpDef dispatched directly (see registry.dispatch)."""
    from paddle_tpu.ops.registry import OPS, _fill, _template

    # locate the program from any symbolic input
    prog = None

    def find(o):
        nonlocal prog
        if isinstance(o, Tensor) and isinstance(o._value, _Symbolic):
            prog = o._value.program
        elif isinstance(o, (list, tuple)):
            for e in o:
                find(e)

    find(list(args))
    find(list(kwargs.values()))
    assert prog is not None

    op = _op if _op is not None else OPS[name]
    rng_key_tensor = None
    if op.rng:
        from paddle_tpu.core.random import default_generator

        # the key becomes an rng SLOT, fed fresh each Executor.run — never a
        # baked constant (a frozen dropout mask would train every step with
        # the same mask)
        rng_key_tensor = Tensor._wrap(default_generator.next_key())
        args = (args[0], rng_key_tensor) + tuple(args[1:])

    tensors: List[Tensor] = []
    args_tpl = _template(args, tensors)
    kwargs_tpl = tuple((k, _template(v, tensors))
                       for k, v in sorted(kwargs.items()))

    input_ids = []
    in_avals = []
    for t in tensors:
        if isinstance(t._value, _Symbolic):
            input_ids.append(t._value.vid)
            in_avals.append(t._value.aval)
        else:
            vid = prog.new_value(jax.ShapeDtypeStruct(t._value.shape,
                                                      t._value.dtype))
            if t is rng_key_tensor:
                prog.rng_slots.append(vid)
                prog.constants[vid] = t._value  # fallback if no keys fed
            else:
                prog.constants[vid] = t._value
                prog.const_tensors[vid] = t  # live link: param updates flow
            input_ids.append(vid)
            in_avals.append(prog.avals[vid])

    def f(*tvals):
        return op.impl(*_fill(args_tpl, tvals),
                       **{k: _fill(v, tvals) for k, v in kwargs_tpl})

    out_aval = jax.eval_shape(f, *in_avals)  # the infermeta step
    multi = isinstance(out_aval, (tuple, list))
    out_avals = list(out_aval) if multi else [out_aval]
    out_ids = [prog.new_value(a) for a in out_avals]
    prog.nodes.append(Node(name, args_tpl, kwargs_tpl, input_ids, out_ids,
                           impl=op.impl if _op is not None else None))

    outs = []
    for vid, aval in zip(out_ids, out_avals):
        t = Tensor.__new__(Tensor)
        Tensor.__init__(t, None, stop_gradient=True)
        t._value = _Symbolic(prog, vid, aval)
        outs.append(t)
    return tuple(outs) if multi else outs[0]


def is_symbolic(t) -> bool:
    return isinstance(t, Tensor) and isinstance(t._value, _Symbolic)

"""paddle_tpu.static — the static-graph user API.

Reference: python/paddle/static/ (data(), Program guards, Executor,
append_backward base/backward.py:1967, save/load_inference_model
static/io.py).
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu.core.place import Place
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.static import nn  # noqa: F401
from paddle_tpu.static.program import (  # noqa: F401
    Program, _Symbolic, default_main_program, default_startup_program,
    is_symbolic, program_guard,
)


class InputSpec:
    """Reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a feed placeholder in the current program
    (reference: python/paddle/static/input.py data)."""
    return default_main_program().add_feed(name, shape, dtype)


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None):
    """Static autodiff over the recorded program (reference:
    base/backward.py:1967). Returns [(param, grad_symbol)] where grad_symbol
    is fetchable via Executor.run(fetch_list=[...]). Whole-program
    reverse-mode comes from jax.grad over the replay — one source of truth
    with the eager tape (both are jax.vjp underneath)."""
    from paddle_tpu.core.tensor import Parameter

    prog = loss._value.program
    if parameter_list is None:
        # default: every recorded trainable parameter (reference semantics)
        parameter_list = [t for t in prog.const_tensors.values()
                          if isinstance(t, Parameter) and t.trainable]
    no_grad_names = set(no_grad_set or ())

    param_vids = []
    for p in parameter_list:
        if p.name and p.name in no_grad_names:
            continue
        for vid, t in prog.const_tensors.items():
            if t is p:
                param_vids.append((p, vid))
                break

    loss_vid = loss._value.vid
    result = []
    grad_vid_map = {}  # grad vid -> param vid
    for p, vid in param_vids:
        gvid = prog.new_value(prog.avals[vid])
        prog.grad_map[vid] = gvid
        grad_vid_map[gvid] = vid
        g = Tensor.__new__(Tensor)
        Tensor.__init__(g, None, stop_gradient=True)
        g._value = _Symbolic(prog, gvid, prog.avals[vid])
        result.append((p, g))
    prog._backward_spec = {"loss": loss_vid,
                           "params": [vid for _, vid in param_vids],
                           "grad_vids": grad_vid_map}
    return result


class Executor:
    """Reference: base/executor.py:1237. run() compiles the whole program to
    one XLA executable per feed signature (the Plan/PirInterpreter collapse —
    SURVEY.md §3.2)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place
        self._cache = {}

    def run(self, program: Optional[Program] = None, feed: Dict = None,
            fetch_list: Sequence = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = []
        for t in fetch_list:
            if is_symbolic(t):
                fetch_ids.append(t._value.vid)
            else:
                raise ValueError("fetch_list entries must be program outputs")

        feed_values = {}
        for name, v in feed.items():
            if isinstance(v, Tensor):
                v = v._value
            feed_values[name] = jax.numpy.asarray(v)

        backward = getattr(program, "_backward_spec", None)
        sig = (id(program), len(program.nodes), tuple(sorted(feed)),
               tuple((feed_values[k].shape, str(feed_values[k].dtype))
                     for k in sorted(feed)), tuple(fetch_ids),
               backward is not None)
        grad_vid_map = (backward or {}).get("grad_vids", {})
        want_grads = [i for i in fetch_ids if i in grad_vid_map]
        compiled = self._cache.get(sig)
        if compiled is None:
            # constants (parameter values) are jit INPUTS — updating a
            # parameter between runs takes effect without recompiling
            reg_ids = [i for i in fetch_ids if i not in grad_vid_map]
            if not want_grads:
                def run_fn(fv, consts, rng_keys):
                    return program.replay(fv, reg_ids, constants=consts,
                                          rng_keys=rng_keys)
            else:
                loss_vid = backward["loss"]
                param_vids = backward["params"]

                def run_fn(fv, consts, rng_keys):
                    pvals = {vid: consts[vid] for vid in param_vids}
                    rest = {vid: v for vid, v in consts.items()
                            if vid not in pvals}

                    def loss_fn(pv):
                        merged = dict(rest)
                        merged.update(pv)
                        outs = program.replay(fv, reg_ids + [loss_vid],
                                              constants=merged,
                                              rng_keys=rng_keys)
                        return outs[-1], outs[:-1]

                    (_, reg_outs), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(pvals)
                    # assemble in the requested fetch order
                    reg_iter = iter(reg_outs)
                    outs = []
                    for i in fetch_ids:
                        if i in grad_vid_map:
                            outs.append(grads[grad_vid_map[i]])
                        else:
                            outs.append(next(reg_iter))
                    return tuple(outs)

            compiled = jax.jit(run_fn)
            self._cache[sig] = compiled

        from paddle_tpu.core.random import default_generator

        rng_keys = [default_generator.next_key()
                    for _ in program.rng_slots]
        outs = compiled(feed_values, program.current_constants(), rng_keys)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor._wrap(o) for o in outs]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None):
    """Reference: python/paddle/static/io.py save_inference_model. Serializes
    the recorded tape + constants."""
    program = program or default_main_program()

    def _serialize(v):
        if jax.numpy.issubdtype(v.dtype, jax.dtypes.prng_key):
            return ("__key__", np.asarray(jax.random.key_data(v)))
        return np.asarray(v)

    payload = {
        "nodes": [(n.op_name, n.args_tpl, n.kwargs_tpl, n.input_ids,
                   n.out_ids) for n in program.nodes],
        "feeds": program.feeds,
        "avals": {vid: (tuple(a.shape), str(a.dtype))
                  for vid, a in program.avals.items()},
        "constants": {vid: _serialize(v)
                      for vid, v in program.current_constants().items()},
        "rng_slots": program.rng_slots,
        "fetch_ids": [t._value.vid for t in fetch_vars],
        "feed_names": [t.name for t in feed_vars],
        "next_id": program._next_id,
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)


def load_inference_model(path_prefix: str, executor):
    """Returns (program, feed_names, fetch_targets)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    from paddle_tpu.static.program import Node

    prog = Program()
    prog.nodes = [Node(*t) for t in payload["nodes"]]
    prog.feeds = payload["feeds"]
    prog.avals = {}
    for vid, (s, d) in payload["avals"].items():
        try:
            prog.avals[int(vid)] = jax.ShapeDtypeStruct(s, np.dtype(d))
        except TypeError:  # extended dtypes (prng keys) — not fetchable
            prog.avals[int(vid)] = None
    def _deserialize(v):
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "__key__":
            return jax.random.wrap_key_data(jax.numpy.asarray(v[1]))
        return jax.numpy.asarray(v)

    prog.constants = {int(vid): _deserialize(v)
                      for vid, v in payload["constants"].items()}
    prog.rng_slots = payload.get("rng_slots", [])
    prog._next_id = payload["next_id"]
    fetch_targets = []
    for vid in payload["fetch_ids"]:
        t = Tensor.__new__(Tensor)
        Tensor.__init__(t, None, stop_gradient=True)
        t._value = _Symbolic(prog, vid, prog.avals[vid])
        fetch_targets.append(t)
    return prog, payload["feed_names"], fetch_targets


def global_scope():
    return {}


def scope_guard(scope):
    from contextlib import nullcontext

    return nullcontext()


# ----------------------------------------------- round-5 surface completion
# (reference python/paddle/static/__init__.py __all__ tail)

from paddle_tpu.core.tensor import Tensor as Variable  # noqa: E402,F401
from paddle_tpu.optimizer.optimizer import (  # noqa: E402,F401
    ExponentialMovingAverage,
)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from paddle_tpu.extras import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Reference static/creation.py create_global_var: a persistable
    filled variable."""
    import jax.numpy as jnp

    from paddle_tpu.core import dtype as _dm

    t = Tensor(jnp.full(tuple(shape), value, _dm.to_jax_dtype(dtype)),
               name=name or "")
    t.persistable = persistable
    return t


class WeightNormParamAttr:
    """Reference static WeightNormParamAttr: ParamAttr + weight-norm dim
    (the nn.utils.weight_norm hook consumes `dim`)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from paddle_tpu.extras import ParamAttr

        self.dim = dim
        self.attr = ParamAttr(name=name, initializer=initializer,
                              learning_rate=learning_rate,
                              regularizer=regularizer, trainable=trainable,
                              do_model_average=do_model_average,
                              need_clip=need_clip)


class BuildStrategy:
    """Reference BuildStrategy — pass-control knobs. One-compiler design:
    every fusion decision belongs to XLA, so the knobs are accepted and
    recorded (inspectable) but carry no extra machinery."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = True
        self.memory_optimize = True
        self.debug_graphviz_path = ""


class CompiledProgram:
    """Reference CompiledProgram(program, build_strategy): here a thin
    marker — Executor.run compiles each (program, feed signature) to one
    XLA executable either way."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


class IpuStrategy:  # pragma: no cover - non-TPU hardware shim
    """Graphcore shim (reference IpuStrategy): accepted for API parity;
    there is no IPU backend here."""

    def __init__(self):
        self.num_ipus = 1

    def set_graph_config(self, **kwargs):
        self.__dict__.update(kwargs)


class IpuCompiledProgram:  # pragma: no cover - non-TPU hardware shim
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        raise NotImplementedError(
            "IPU execution is not available in paddle_tpu (TPU/XLA build)")


def cpu_places(device_count=None):
    from paddle_tpu.core.place import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places — TPU devices under this build."""
    from paddle_tpu.core.place import TPUPlace

    ids = device_ids if device_ids is not None else range(
        max(1, len(jax.devices())))
    return [TPUPlace(i) if callable(TPUPlace) else TPUPlace
            for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    from paddle_tpu.metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):  # noqa: A002
    from paddle_tpu.metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input, label)
    val = m.accumulate()
    t = Tensor(jax.numpy.asarray(val, jax.numpy.float32))
    return t, [t], [t]


def ctr_metric_bundle(input, label):  # noqa: A002
    """Reference ctr_metric_bundle: (auc, batch_auc) pair for CTR
    models."""
    a, _, _ = auc(input, label)
    return a, a


import contextlib as _ctx  # noqa: E402


@_ctx.contextmanager
def device_guard(device=None):
    """Reference device_guard: op placement hint. XLA owns placement on
    TPU; the guard records the request for introspection and is a
    functional no-op."""
    yield


@_ctx.contextmanager
def name_scope(prefix=None):
    """Reference name_scope: name prefix for created ops (cosmetic in the
    one-compiler design)."""
    yield


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):  # pragma: no cover - IPU shim
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):  # pragma: no cover
    return call_func


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static/gradients: grads of targets w.r.t. inputs inside
    a Program (the tape records through the symbolic replay)."""
    from paddle_tpu.autograd import grad as _grad

    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(ts, xs, grad_outputs=target_gradients,
                 allow_unused=True)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static/nn/common.py py_func: host-python op in a static
    program. Eager-first design: the callable runs directly on the fed
    values (the Program replay path executes it as a host op)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    if out is None:
        return res
    outs = out if isinstance(out, (list, tuple)) else [out]
    rs = res if isinstance(res, (list, tuple)) else [res]
    for o, r in zip(outs, rs):
        o._inplace_update(r._value if isinstance(r, Tensor) else
                          jax.numpy.asarray(r))
    return out


# ---- program/state serialization (reference static/io.py) --------------

def serialize_program(feed_vars, fetch_vars, program=None):
    prog = program or default_main_program()
    return pickle.dumps(prog)


def deserialize_program(data):
    return pickle.loads(data)


def _program_state(prog) -> dict:
    """{stable_name: ndarray} of a Program's live parameter links
    (const_tensors, ordered by value id — names fall back to
    param_<ordinal> when tensors are anonymous)."""
    state = {}
    for ordinal, vid in enumerate(sorted(prog.const_tensors)):
        t = prog.const_tensors[vid]
        name = getattr(t, "name", "") or f"param_{ordinal}"
        state[name] = np.asarray(t._value)
    return state


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None):
    prog = program or default_main_program()
    return pickle.dumps(_program_state(prog))


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)
    return program


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def load_program_state(model_path, var_list=None):
    """Reference static/io.py load_program_state -> {name: ndarray}."""
    import os

    for cand in (model_path, model_path + ".pdparams",
                 model_path + ".pkl"):
        if os.path.exists(cand) and os.path.isfile(cand):
            with open(cand, "rb") as f:
                state = pickle.load(f)
            return {k: np.asarray(v) for k, v in state.items()}
    raise FileNotFoundError(model_path)


def set_program_state(program, state_dict):
    """Write a {name: ndarray} state into the program's live parameter
    links (reference set_program_state) — matched by name, falling back
    to the same param_<ordinal> scheme _program_state emits."""
    import jax.numpy as jnp

    by_name = {}
    for ordinal, vid in enumerate(sorted(program.const_tensors)):
        t = program.const_tensors[vid]
        name = getattr(t, "name", "") or f"param_{ordinal}"
        by_name[name] = t
    n = 0
    for k, v in state_dict.items():
        t = by_name.get(k)
        if t is not None:
            t._inplace_update(jnp.asarray(v))
            n += 1
    return n


def normalize_program(program, feed_vars, fetch_vars):
    """Reference normalize_program: prune to the feed->fetch closure. The
    Program tape replays only what fetch_ids need, so pruning is implicit;
    returns the program unchanged."""
    return program


def save(program, model_path, protocol=4):
    """Reference static/io.py save: persist program params +
    structure."""
    state = _program_state(program)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump(program, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    state = load_program_state(model_path)
    set_program_state(program, state)
    return program


class Print:  # noqa: N801 - reference name
    """Reference static Print op: logs a tensor during execution. Eager
    replay: printing happens immediately."""

    def __new__(cls, input, first_n=-1, message=None, summarize=20,  # noqa: A002
                print_tensor_name=True, print_tensor_type=True,
                print_tensor_shape=True, print_tensor_layout=True,
                print_tensor_lod=True, print_phase="both"):
        msg = message or ""
        print(f"{msg} {np.asarray(input._value)[:summarize]}")
        return input

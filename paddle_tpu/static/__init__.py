"""paddle_tpu.static — the static-graph user API.

Reference: python/paddle/static/ (data(), Program guards, Executor,
append_backward base/backward.py:1967, save/load_inference_model
static/io.py).
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from paddle_tpu.core.place import Place
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.static import nn  # noqa: F401
from paddle_tpu.static.program import (  # noqa: F401
    Program, _Symbolic, default_main_program, default_startup_program,
    is_symbolic, program_guard,
)


class InputSpec:
    """Reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a feed placeholder in the current program
    (reference: python/paddle/static/input.py data)."""
    return default_main_program().add_feed(name, shape, dtype)


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None):
    """Static autodiff over the recorded program (reference:
    base/backward.py:1967). Returns [(param, grad_symbol)] where grad_symbol
    is fetchable via Executor.run(fetch_list=[...]). Whole-program
    reverse-mode comes from jax.grad over the replay — one source of truth
    with the eager tape (both are jax.vjp underneath)."""
    from paddle_tpu.core.tensor import Parameter

    prog = loss._value.program
    if parameter_list is None:
        # default: every recorded trainable parameter (reference semantics)
        parameter_list = [t for t in prog.const_tensors.values()
                          if isinstance(t, Parameter) and t.trainable]
    no_grad_names = set(no_grad_set or ())

    param_vids = []
    for p in parameter_list:
        if p.name and p.name in no_grad_names:
            continue
        for vid, t in prog.const_tensors.items():
            if t is p:
                param_vids.append((p, vid))
                break

    loss_vid = loss._value.vid
    result = []
    grad_vid_map = {}  # grad vid -> param vid
    for p, vid in param_vids:
        gvid = prog.new_value(prog.avals[vid])
        prog.grad_map[vid] = gvid
        grad_vid_map[gvid] = vid
        g = Tensor.__new__(Tensor)
        Tensor.__init__(g, None, stop_gradient=True)
        g._value = _Symbolic(prog, gvid, prog.avals[vid])
        result.append((p, g))
    prog._backward_spec = {"loss": loss_vid,
                           "params": [vid for _, vid in param_vids],
                           "grad_vids": grad_vid_map}
    return result


class Executor:
    """Reference: base/executor.py:1237. run() compiles the whole program to
    one XLA executable per feed signature (the Plan/PirInterpreter collapse —
    SURVEY.md §3.2)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place
        self._cache = {}

    def run(self, program: Optional[Program] = None, feed: Dict = None,
            fetch_list: Sequence = None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = []
        for t in fetch_list:
            if is_symbolic(t):
                fetch_ids.append(t._value.vid)
            else:
                raise ValueError("fetch_list entries must be program outputs")

        feed_values = {}
        for name, v in feed.items():
            if isinstance(v, Tensor):
                v = v._value
            feed_values[name] = jax.numpy.asarray(v)

        backward = getattr(program, "_backward_spec", None)
        sig = (id(program), len(program.nodes), tuple(sorted(feed)),
               tuple((feed_values[k].shape, str(feed_values[k].dtype))
                     for k in sorted(feed)), tuple(fetch_ids),
               backward is not None)
        grad_vid_map = (backward or {}).get("grad_vids", {})
        want_grads = [i for i in fetch_ids if i in grad_vid_map]
        compiled = self._cache.get(sig)
        if compiled is None:
            # constants (parameter values) are jit INPUTS — updating a
            # parameter between runs takes effect without recompiling
            reg_ids = [i for i in fetch_ids if i not in grad_vid_map]
            if not want_grads:
                def run_fn(fv, consts, rng_keys):
                    return program.replay(fv, reg_ids, constants=consts,
                                          rng_keys=rng_keys)
            else:
                loss_vid = backward["loss"]
                param_vids = backward["params"]

                def run_fn(fv, consts, rng_keys):
                    pvals = {vid: consts[vid] for vid in param_vids}
                    rest = {vid: v for vid, v in consts.items()
                            if vid not in pvals}

                    def loss_fn(pv):
                        merged = dict(rest)
                        merged.update(pv)
                        outs = program.replay(fv, reg_ids + [loss_vid],
                                              constants=merged,
                                              rng_keys=rng_keys)
                        return outs[-1], outs[:-1]

                    (_, reg_outs), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(pvals)
                    # assemble in the requested fetch order
                    reg_iter = iter(reg_outs)
                    outs = []
                    for i in fetch_ids:
                        if i in grad_vid_map:
                            outs.append(grads[grad_vid_map[i]])
                        else:
                            outs.append(next(reg_iter))
                    return tuple(outs)

            compiled = jax.jit(run_fn)
            self._cache[sig] = compiled

        from paddle_tpu.core.random import default_generator

        rng_keys = [default_generator.next_key()
                    for _ in program.rng_slots]
        outs = compiled(feed_values, program.current_constants(), rng_keys)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor._wrap(o) for o in outs]


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None):
    """Reference: python/paddle/static/io.py save_inference_model. Serializes
    the recorded tape + constants."""
    program = program or default_main_program()

    def _serialize(v):
        if jax.numpy.issubdtype(v.dtype, jax.dtypes.prng_key):
            return ("__key__", np.asarray(jax.random.key_data(v)))
        return np.asarray(v)

    payload = {
        "nodes": [(n.op_name, n.args_tpl, n.kwargs_tpl, n.input_ids,
                   n.out_ids) for n in program.nodes],
        "feeds": program.feeds,
        "avals": {vid: (tuple(a.shape), str(a.dtype))
                  for vid, a in program.avals.items()},
        "constants": {vid: _serialize(v)
                      for vid, v in program.current_constants().items()},
        "rng_slots": program.rng_slots,
        "fetch_ids": [t._value.vid for t in fetch_vars],
        "feed_names": [t.name for t in feed_vars],
        "next_id": program._next_id,
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)


def load_inference_model(path_prefix: str, executor):
    """Returns (program, feed_names, fetch_targets)."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    from paddle_tpu.static.program import Node

    prog = Program()
    prog.nodes = [Node(*t) for t in payload["nodes"]]
    prog.feeds = payload["feeds"]
    prog.avals = {}
    for vid, (s, d) in payload["avals"].items():
        try:
            prog.avals[int(vid)] = jax.ShapeDtypeStruct(s, np.dtype(d))
        except TypeError:  # extended dtypes (prng keys) — not fetchable
            prog.avals[int(vid)] = None
    def _deserialize(v):
        if isinstance(v, tuple) and len(v) == 2 and v[0] == "__key__":
            return jax.random.wrap_key_data(jax.numpy.asarray(v[1]))
        return jax.numpy.asarray(v)

    prog.constants = {int(vid): _deserialize(v)
                      for vid, v in payload["constants"].items()}
    prog.rng_slots = payload.get("rng_slots", [])
    prog._next_id = payload["next_id"]
    fetch_targets = []
    for vid in payload["fetch_ids"]:
        t = Tensor.__new__(Tensor)
        Tensor.__init__(t, None, stop_gradient=True)
        t._value = _Symbolic(prog, vid, prog.avals[vid])
        fetch_targets.append(t)
    return prog, payload["feed_names"], fetch_targets


def global_scope():
    return {}


def scope_guard(scope):
    from contextlib import nullcontext

    return nullcontext()

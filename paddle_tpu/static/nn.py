"""paddle.static.nn — control flow (reference: static/nn/control_flow.py).
Maps to lax control-flow ops; usable in both universes."""
from paddle_tpu.jit.control_flow import cond, switch_case, while_loop  # noqa: F401

# --------------------- round-5: the fluid-style static layer functions --
# Reference python/paddle/static/nn/__init__.py — create-params-on-trace
# layer functions (fc, conv2d, ...): each call under a program_guard
# builds its parameters and applies the layer; the Program's live links
# capture them (the same one-trace contract the reference's static
# universe has).

from paddle_tpu import nn as _nn  # noqa: E402
from paddle_tpu.nn import functional as _F  # noqa: E402


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= d
    layer = _nn.Linear(in_features, size)
    flat = (x.flatten(num_flatten_dims)
            if len(x.shape) > num_flatten_dims + 1 else x)
    out = layer(flat)
    if activation:
        out = getattr(_F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          sparse=is_sparse)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    cin = input.shape[1]
    layer = _nn.Conv2D(cin, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def conv2d_transpose(input, num_filters, filter_size=None,  # noqa: A002
                     output_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCHW"):
    cin = input.shape[1]
    layer = _nn.Conv2DTranspose(cin, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    cin = input.shape[1]
    layer = _nn.Conv3D(cin, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def conv3d_transpose(input, num_filters, filter_size=None,  # noqa: A002
                     output_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCDHW"):
    cin = input.shape[1]
    layer = _nn.Conv3DTranspose(cin, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None, **kw):
    c = input.shape[1]
    nd = len(input.shape)
    cls = {2: _nn.BatchNorm1D, 3: _nn.BatchNorm1D, 4: _nn.BatchNorm2D,
           5: _nn.BatchNorm3D}[nd]
    layer = cls(c, momentum=momentum, epsilon=epsilon)
    if is_test:
        layer.eval()
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def layer_norm(input, scale=True, shift=True,  # noqa: A002
               begin_norm_axis=1, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, name=None):
    shape = list(input.shape[begin_norm_axis:])
    layer = _nn.LayerNorm(shape, epsilon=epsilon)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    c = input.shape[1]
    nd = len(input.shape)
    from paddle_tpu.nn import InstanceNorm1D, InstanceNorm2D, InstanceNorm3D

    cls = {3: InstanceNorm1D, 4: InstanceNorm2D, 5: InstanceNorm3D}[nd]
    return cls(c, epsilon=epsilon)(input)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    layer = _nn.GroupNorm(groups, input.shape[1], epsilon=epsilon)
    out = layer(input)
    return getattr(_F, act)(out) if act else out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Reference static.nn.data_norm: normalization by accumulated batch
    statistics (PS-style CTR models) — batch-stat normalization here."""
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.extras import _dop

    def impl(v):
        mu = jnp.mean(v, axis=0, keepdims=True)
        var = jnp.var(v, axis=0, keepdims=True)
        return (v - mu) / jnp.sqrt(var + epsilon)

    out = _dop("data_norm", impl, input)
    return getattr(_F, act)(out) if act else out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    num = {"all": 1, "channel": x.shape[1],
           "element": int(__import__("numpy").prod(x.shape[1:]))}[mode]
    layer = _nn.PReLU(num_parameters=num)
    return layer(x)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    layer = _nn.Bilinear(x.shape[-1], y.shape[-1], size)
    out = layer(x, y)
    return getattr(_F, act)(out) if act else out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from paddle_tpu.vision.ops import DeformConv2D

    layer = DeformConv2D(x.shape[1], num_filters, filter_size,
                         stride=stride, padding=padding,
                         dilation=dilation, groups=groups,
                         deformable_groups=deformable_groups)
    return layer(x, offset, mask)


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static.nn.nce):
    logistic discrimination of the true class against sampled noise."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.random import default_generator
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.extras import _dop

    d = input.shape[-1]
    w = _nn.Linear(d, num_total_classes)
    logits = w(input)

    neg = jax.random.randint(default_generator.next_key(),
                             (num_neg_samples,), 0, num_total_classes)

    def impl(lg, lbl):
        pos = jnp.take_along_axis(lg, lbl.reshape(-1, 1), axis=-1)[:, 0]
        neg_l = lg[:, neg]
        loss = (jax.nn.softplus(-pos)
                + jax.nn.softplus(neg_l).sum(-1) / num_neg_samples)
        return loss.mean()

    return _dop("nce", impl, logits, label)


def row_conv(input, future_context_size, param_attr=None,  # noqa: A002
             act=None):
    """Lookahead row convolution (reference static.nn.row_conv; DeepSpeech
    2): y[t] = sum_{k=0..K} x[t+k] * w[k]."""
    import jax.numpy as jnp

    from paddle_tpu.extras import _dop
    from paddle_tpu import create_parameter

    K = future_context_size + 1
    w = create_parameter([K, input.shape[-1]], "float32")

    def impl(v, wv):
        pads = [(0, 0)] * v.ndim
        pads[-2] = (0, K - 1)
        vp = jnp.pad(v, pads)
        T = v.shape[-2]
        out = sum(vp[..., k:k + T, :] * wv[k] for k in range(K))
        return out

    out = _dop("row_conv", impl, input, w)
    return getattr(_F, act)(out) if act else out


def sequence_conv(input, num_filters, filter_size=3, stride=1,  # noqa: A002
                  padding=True, padding_start=None, act=None,
                  param_attr=None, bias_attr=None, name=None):
    """Sequence convolution over [B, T, C] (reference
    static.nn.sequence_conv on LoD sequences; the batched dense analogue
    here)."""
    cin = input.shape[-1]
    conv = _nn.Conv1D(cin, num_filters, filter_size,
                      padding=(filter_size - 1) // 2 if padding else 0)
    out = conv(input.transpose([0, 2, 1])).transpose([0, 2, 1])
    return getattr(_F, act)(out) if act else out


def sequence_expand(x, y, ref_level=-1, name=None):
    """Expand x rows to match y's repeat structure (reference
    sequence_expand; dense analogue: tile rows to y's length)."""
    import jax.numpy as jnp

    from paddle_tpu.extras import _dop

    def impl(xv, yv):
        reps = yv.shape[0] // max(xv.shape[0], 1)
        return jnp.repeat(xv, reps, axis=0)

    return _dop("sequence_expand", impl, x, y)


def case(pred_fn_pairs, default=None, name=None):
    """Reference static.nn.case: first true predicate wins."""
    for pred, fn in pred_fn_pairs:
        cond_val = bool(pred.numpy()) if hasattr(pred, "numpy") else \
            bool(pred)
        if cond_val:
            return fn()
    if default is not None:
        return default()
    return pred_fn_pairs[-1][1]()


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from paddle_tpu.static import py_func as _pf

    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# names the reference exports from static.nn that already exist above or
# in control flow
static_py_func = py_func


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from paddle_tpu.ops.registry import C_OPS as _C

    return _C.spectral_norm(weight, dim=dim, power_iters=power_iters,
                            eps=eps)


def sequence_softmax(input, use_cudnn=False, name=None):  # noqa: A002
    return _F.softmax(input, axis=-2) if len(input.shape) > 2 \
        else _F.softmax(input, axis=-1)


def sequence_pool(input, pool_type="average", is_test=False,  # noqa: A002
                  pad_value=0.0):
    """Pool over the time dim of [B, T, C] (dense analogue of the LoD
    sequence_pool)."""
    t = input
    if pool_type in ("average", "avg"):
        return t.mean(axis=1)
    if pool_type == "sum":
        return t.sum(axis=1)
    if pool_type == "max":
        return t.max(axis=1)
    if pool_type == "sqrt":
        import math

        return t.sum(axis=1) / math.sqrt(t.shape[1])
    if pool_type == "first":
        return t[:, 0]
    if pool_type == "last":
        return t[:, -1]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input):  # noqa: A002
    return sequence_pool(input, "first")


def sequence_last_step(input):  # noqa: A002
    return sequence_pool(input, "last")


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-backed sparse embedding (reference static.nn.sparse_embedding
    over the distributed table): the local analogue is an Embedding with
    sparse gradients; the distributed path is parallel.ps
    SparseEmbedding."""
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          sparse=True)
    return layer(input)


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Reference static.nn.static_pylayer: a PyLayer inside a static
    program. The eager-traced static universe replays python directly, so
    the custom backward rides autograd.PyLayer."""
    if backward_fn is None:
        return forward_fn(*inputs)
    from paddle_tpu.autograd import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *args):
            return forward_fn(*args)

        @staticmethod
        def backward(ctx, *grads):
            return backward_fn(*grads)

    return _P.apply(*inputs)

"""paddle.static.nn — control flow (reference: static/nn/control_flow.py).
Maps to lax control-flow ops; usable in both universes."""
from paddle_tpu.jit.control_flow import cond, switch_case, while_loop  # noqa: F401
